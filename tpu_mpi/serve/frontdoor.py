"""The event-driven session front door: C10k on the native poll engine.

The legacy front door costs one Python thread per attached session — fine
for tens of tenants, a ceiling at thousands (docs/serving.md "Front
door"). This module replaces it with the classic event-driven shape:

    listener ─┐
    session ──┤  edge-triggered readiness loop (1 thread, tmfd_* epoll
    session ──┤  engine in _native/transport.cc; select.epoll fallback)
    session ──┘        │ parsed frames
                  ReadyRing (FIFO across connections, dedup)
                       │
              fixed worker pool (serve_workers threads)
                       │
             the UNCHANGED broker admission path
             (attach_tenant / _serve_op / revoke_lease)

- **One loop thread** owns every socket's read side: it drains readable
  sockets into per-connection incremental frame parsers. An idle attached
  session costs one fd and a parser struct — no thread, no stack.
- **Inbound recv leases**: OP payload blobs land zero-copy in registered
  buffers recycled across frames (the inbound mirror of the outbound
  sendmsg scatter-gather path). A buffer is recycled only when nothing
  views it anymore (BufferError probe), so a payload still referenced by
  an in-flight op can never be clobbered.
- **A fixed worker pool** services complete frames; per-connection order
  is preserved (``busy`` bit — one worker per connection at a time), and
  the pool size bounds frame concurrency while the socket count scales
  independently.
- Writes go through :class:`_SendSock`, a blocking-send facade over the
  nonblocking fd, so ``protocol.send_frame`` and the whole broker reply
  path run unchanged on both transports.

The serve contracts (lease grammar, typed errors, DRR fairness, T208
accounting) are transport-blind: `TPU_MPI_SERVE_TRANSPORT` flips between
this module and the thread-per-connection path, and the same test suite
runs against both.
"""

from __future__ import annotations

import json
import os
import select
import socket
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from .. import config
from .. import locksmith
from .. import perfvars
from ..error import MPIError, SessionError
from . import protocol
from .queueing import ReadyRing


def _make_engine():
    """The readiness engine: native epoll (tmfd_* in transport.cc) when the
    toolchain can build it, ``select.epoll`` otherwise. Both speak the same
    (fd, bits) event tuples; bit 1 = readable/hangup, bit 2 = writable."""
    try:
        from .._native import NativeFdEngine
        return NativeFdEngine(), "native"
    except Exception:
        return _PyFdEngine(), "python"


class _PyFdEngine:
    """select.epoll fallback mirroring NativeFdEngine's surface (same
    edge-triggered semantics, same wake-pipe cross-thread wakeup)."""

    def __init__(self):
        self._ep = select.epoll()
        self._wake_rd, self._wake_wr = os.pipe()
        os.set_blocking(self._wake_rd, False)
        os.set_blocking(self._wake_wr, False)
        self._ep.register(self._wake_rd, select.EPOLLIN)

    def register(self, fd: int, want_write: bool = False) -> None:
        os.set_blocking(fd, False)
        ev = select.EPOLLIN | select.EPOLLRDHUP | select.EPOLLET
        if want_write:
            ev |= select.EPOLLOUT
        self._ep.register(fd, ev)

    def modify(self, fd: int, want_write: bool) -> None:
        ev = select.EPOLLIN | select.EPOLLRDHUP | select.EPOLLET
        if want_write:
            ev |= select.EPOLLOUT
        self._ep.modify(fd, ev)

    def unregister(self, fd: int) -> None:
        try:
            self._ep.unregister(fd)
        except OSError:
            pass

    def wait(self, timeout: float) -> List[tuple]:
        try:
            events = self._ep.poll(timeout)
        except InterruptedError:
            return []
        out = []
        rd_bits = (select.EPOLLIN | select.EPOLLRDHUP | select.EPOLLHUP
                   | select.EPOLLERR)
        for fd, ev in events:
            if fd == self._wake_rd:
                try:
                    while os.read(self._wake_rd, 256):
                        pass
                except BlockingIOError:
                    pass
                out.append((-1, 0))
                continue
            bits = (1 if ev & rd_bits else 0) | (2 if ev & select.EPOLLOUT
                                                 else 0)
            out.append((fd, bits))
        return out

    def wake(self) -> None:
        try:
            os.write(self._wake_wr, b"\x01")
        except (BlockingIOError, OSError):
            pass                      # a full pipe already holds a wakeup

    def close(self) -> None:
        self._ep.close()
        for fd in (self._wake_rd, self._wake_wr):
            try:
                os.close(fd)
            except OSError:
                pass


class RecvLeasePool:
    """Registered inbound buffers: payload blobs at or under the lease
    window land in a recycled ``bytearray`` (a *hit* — steady-state ops
    allocate nothing on the receive side); larger blobs get a per-frame
    exact-size buffer (a *miss*). Recycling is safe by construction: a
    buffer is reused only when the BufferError probe proves nothing
    exports it anymore (append on a bytearray with live memoryview or
    ndarray exports raises BEFORE mutating) — a stale view can never
    watch its bytes change underneath.

    Returned buffers that still carry exports go to a *quarantine* lane,
    re-probed on later acquires, rather than straight to the GC: the op
    path legitimately outlives the frame by one call — the collective
    auto-arm table (overlap.PlanCache.auto_note) pins each signature's
    most recent operand for its identity streak, releasing it when the
    next op replaces it — so quarantine converts that one-op lag into
    steady-state hits instead of a 100% drop rate."""

    def __init__(self, window: int, capacity: int = 64):
        self.window = max(4096, int(window))
        self.capacity = int(capacity)
        self._free: deque = deque()
        self._quar: deque = deque()
        self._lock = locksmith.make_lock("frontdoor.leasepool")
        self.hits = 0
        self.misses = 0
        self.drops = 0
        self.recycled = 0

    @staticmethod
    def _exported(buf: bytearray) -> bool:
        try:
            buf.append(0)
            buf.pop()
            return False
        except BufferError:
            return True

    def _sweep_locked(self) -> None:
        """Re-probe quarantined buffers; the released ones rejoin the
        freelist (each probed once per sweep)."""
        for _ in range(len(self._quar)):
            buf = self._quar.popleft()
            if self._exported(buf):
                self._quar.append(buf)
            elif len(self._free) < self.capacity:
                self._free.append(buf)
                self.recycled += 1

    def acquire(self, nbytes: int) -> bytearray:
        if nbytes <= self.window:
            with self._lock:
                if not self._free and self._quar:
                    self._sweep_locked()
                if self._free:
                    self.hits += 1
                    return self._free.popleft()
                self.misses += 1
            return bytearray(self.window)
        with self._lock:
            self.misses += 1
        return bytearray(nbytes)

    def recycle(self, buf: bytearray) -> None:
        if len(buf) != self.window:
            return                     # oversize one-shot: GC owns it
        with self._lock:
            if self._exported(buf):
                if len(self._quar) < self.capacity:
                    self._quar.append(buf)
                else:
                    self.drops += 1    # quarantine full: GC owns it
            elif len(self._free) < self.capacity:
                self._free.append(buf)
                self.recycled += 1

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {"window": self.window, "hits": self.hits,
                    "misses": self.misses, "drops": self.drops,
                    "recycled": self.recycled,
                    "quarantined": len(self._quar),
                    "hit_rate": (self.hits / total) if total else 0.0}


class _SendSock:
    """Blocking-send facade over a front-door session socket: the loop
    keeps every fd nonblocking (edge-triggered reads), but the broker's
    reply path expects ``sendall``/``sendmsg`` that finish or raise. On
    EAGAIN this parks the *sending worker* in select-for-writability —
    never the event loop. ``close`` routes through the front door so the
    fd leaves the readiness set before it is returned to the kernel (the
    fd-reuse race closes there, not here)."""

    __slots__ = ("_door", "_conn", "_sock")
    _SEND_TIMEOUT = 60.0

    def __init__(self, door: "FrontDoor", conn: "_Conn"):
        self._door = door
        self._conn = conn
        self._sock = conn.sock

    def fileno(self) -> int:
        return self._sock.fileno()

    def _wait_writable(self, deadline: float) -> None:
        remaining = deadline - time.monotonic()
        if remaining <= 0 or not select.select(
                [], [self._sock], [], min(remaining, 5.0))[1]:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    "session send stalled: peer is not draining")

    def sendmsg(self, buffers) -> int:
        deadline = time.monotonic() + self._SEND_TIMEOUT
        while True:
            try:
                return self._sock.sendmsg(buffers)
            except BlockingIOError:
                self._wait_writable(deadline)   # lock: blocking

    def sendall(self, data) -> None:
        view = memoryview(data).cast("B")
        deadline = time.monotonic() + self._SEND_TIMEOUT
        while view.nbytes:
            try:
                sent = self._sock.send(view)
                view = view[sent:]
            except BlockingIOError:
                self._wait_writable(deadline)   # lock: blocking

    def close(self) -> None:
        self._door._close_conn(self._conn)

    def getpeername(self):
        return self._sock.getpeername()


# parser stages
_S_HDR, _S_JSON, _S_BLOBLEN, _S_BLOB = range(4)

# per-connection parsed-frame high-water mark: the loop stops feeding a
# connection's parser past this backlog (the legacy thread had natural
# one-frame-at-a-time backpressure; this bounds a pipelining client to a
# fixed number of in-memory frames) and resumes when workers drain below it
_FRAME_HWM = 32


class _Conn:
    """One attached (or attaching) session socket: the incremental frame
    parser the loop thread feeds, the frame queue workers drain, and the
    service bits (``queued`` for the ReadyRing, ``busy`` for per-connection
    order). Only the loop thread touches parser state; workers touch only
    ``frames`` and the service bits (under ``lock``)."""

    __slots__ = ("sock", "fd", "door", "frames", "lock", "queued", "busy",
                 "closed", "dead_read", "paused", "lease", "proxy",
                 "accepted_at",
                 "_stage", "_want", "_got", "_buf", "_view", "_kind",
                 "_json_len", "_nblobs", "_meta", "_blobs", "_bufs",
                 "_blob_i")

    def __init__(self, sock: socket.socket, door: "FrontDoor"):
        self.sock = sock
        self.fd = sock.fileno()
        self.door = door
        self.frames: deque = deque()   # (kind, meta, arrays, bufs) | sentinel
        self.lock = locksmith.make_lock("frontdoor.conn")
        self.queued = False            # owned by the ReadyRing
        self.busy = False              # a worker is servicing this conn
        self.closed = False
        self.dead_read = False         # EOF/corrupt: stop feeding the parser
        self.paused = False            # frame backlog >= _FRAME_HWM
        self.lease = None              # set after a successful attach
        self.proxy = _SendSock(door, self)
        self.accepted_at = time.monotonic()
        self._reset_parser()

    def _reset_parser(self) -> None:
        self._stage = _S_HDR
        self._want = protocol._HDR.size
        self._got = 0
        self._buf = bytearray(self._want)
        self._view = memoryview(self._buf)
        self._kind = 0
        self._json_len = 0
        self._nblobs = 0
        self._meta: dict = {}
        self._blobs: list = []
        self._bufs: list = []
        self._blob_i = 0

    # -- loop-thread side ----------------------------------------------------
    def feed(self) -> int:
        """Drain the socket (edge-triggered: read to EAGAIN), advancing the
        parser; complete frames land in ``self.frames``. Returns the number
        of frames produced. Raises ``protocol.Disconnect`` on EOF and
        ``SessionError`` on a corrupt stream. Stops early (``paused`` set,
        under ``lock``) once the parsed backlog hits ``_FRAME_HWM`` — the
        resume pump in ``FrontDoor._release`` restarts it when workers
        drain below the mark, so a pipelining client holds at most a
        bounded number of frames in memory."""
        produced = 0
        while True:
            if len(self.frames) >= _FRAME_HWM:
                with self.lock:        # recheck: workers drain concurrently
                    if len(self.frames) >= _FRAME_HWM:
                        self.paused = True
                        return produced
            if self._got < self._want:
                try:
                    n = self.sock.recv_into(self._view[self._got:self._want])
                except (BlockingIOError, InterruptedError):
                    return produced
                except OSError as e:
                    raise protocol.Disconnect(
                        f"connection lost mid-frame: {e}") from None
                if n == 0:
                    raise protocol.Disconnect(
                        "peer closed" if self._stage == _S_HDR
                        and self._got == 0 else "peer closed mid-frame")
                self._got += n
                if self._got < self._want:
                    continue
            produced += self._advance()

    def _advance(self) -> int:
        """One completed parser stage; returns 1 when a frame finished."""
        if self._stage == _S_HDR:
            kind, json_len, nblobs = protocol._HDR.unpack(self._buf)
            if kind not in protocol.KIND_NAMES \
                    or json_len > protocol._MAX_JSON:
                raise SessionError(f"corrupt session frame (kind={kind}, "
                                   f"json_len={json_len})")
            self._kind, self._json_len, self._nblobs = kind, json_len, nblobs
            if json_len:
                self._stage = _S_JSON
                self._retarget(bytearray(json_len), json_len)
                return 0
            self._meta = {}
            return self._after_meta()
        if self._stage == _S_JSON:
            try:
                meta = json.loads(bytes(self._buf).decode())
            except (ValueError, UnicodeDecodeError) as e:
                raise SessionError(
                    f"malformed session frame metadata: {e}") from None
            if not isinstance(meta, dict):
                raise SessionError("session frame metadata must be a JSON "
                                   f"object, got {type(meta).__name__}")
            self._meta = meta
            return self._after_meta()
        if self._stage == _S_BLOBLEN:
            (blen,) = protocol._BLOB.unpack(self._buf)
            if blen > config.load().max_frame_bytes:
                raise SessionError(
                    f"session frame blob of {blen} bytes exceeds "
                    f"max_frame_bytes={config.load().max_frame_bytes}")
            buf = self.door.lease_pool.acquire(blen)
            self._bufs.append(buf)
            self._stage = _S_BLOB
            self._retarget(buf, blen)
            return 0
        # _S_BLOB complete: wrap the filled prefix of the lease buffer
        descs = self._meta.get("blobs") or []
        raw = self._view[:self._want]
        desc = descs[self._blob_i] \
            if isinstance(descs, list) and self._blob_i < len(descs) else None
        try:
            blob = protocol.decode_blob(raw, desc if isinstance(desc, dict)
                                        else None)
        except Exception as e:
            # hostile desc (bad dtype string, shape/size mismatch, missing
            # keys): the client's problem, never the loop thread's
            raise SessionError(
                f"malformed session frame blob descriptor: {e}") from None
        self._blobs.append(blob)
        self._blob_i += 1
        return self._next_blob_or_finish()

    def _retarget(self, buf: bytearray, want: int) -> None:
        self._buf = buf
        self._view = memoryview(buf)
        self._want = want
        self._got = 0

    def _after_meta(self) -> int:
        self._blob_i = 0
        return self._next_blob_or_finish()

    def _next_blob_or_finish(self) -> int:
        if self._blob_i < self._nblobs:
            self._stage = _S_BLOBLEN
            self._retarget(bytearray(protocol._BLOB.size),
                           protocol._BLOB.size)
            return 0
        # a frame is a MUTABLE list so _finish_frame can null the payload
        # slots in place — every holder of the frame loses its alias at
        # once, which is what lets the recycle probe succeed. The parser
        # resets before handoff for the same reason: recycling must see
        # only the op path's views, never the parser's leftovers.
        frame = [self._kind, self._meta, self._blobs, self._bufs]
        self._reset_parser()
        self.frames.append(frame)
        return 1


class FrontDoor:
    """The event-driven session transport of one :class:`Broker`
    (``TPU_MPI_SERVE_TRANSPORT=events``): readiness loop + worker pool +
    recv-lease pool, serving the broker's unchanged admission path."""

    _EOF = object()                    # frame-queue sentinel: peer went away

    def __init__(self, broker, listener: socket.socket):
        cfg = config.load()
        self.broker = broker
        self.listener = listener
        self.nworkers = max(1, int(cfg.serve_workers))
        self.lease_pool = RecvLeasePool(int(cfg.serve_lease_window))
        self._engine, self.engine_kind = _make_engine()
        self._conns: Dict[int, _Conn] = {}
        self._conns_lock = locksmith.make_lock("frontdoor.conns")
        self._ready = ReadyRing()
        self._resume: deque = deque()  # paused conns to re-pump (loop drains)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._busy = 0                 # lock: guard frontdoor.conns
        self.started_at = time.monotonic()
        # loop-thread-owned counters (mirrored to pvars as deltas)
        self.wakeups = 0
        self.frames_in = 0
        self.attaches = 0              # worker-updated, under _conns_lock
        self.peak_sockets = 0
        self._mirrored: Dict[str, int] = {}
        self._last_mirror = 0.0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self.listener.setblocking(False)
        self._engine.register(self.listener.fileno())
        for i in range(self.nworkers):
            t = threading.Thread(target=self._worker, name=f"serve-fd-w{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def serve_forever(self) -> None:
        """The readiness loop (the calling thread becomes the loop thread —
        mirrors Broker.serve_forever's blocking contract)."""
        while not self._stop.is_set():
            try:
                events = self._engine.wait(0.2)
            except OSError:
                break
            self.wakeups += 1
            for fd, bits in events:
                if fd < 0:
                    continue           # cross-thread wakeup
                if fd == self.listener.fileno():
                    self._accept_burst()
                    continue
                conn = self._conns.get(fd)
                if conn is None:
                    continue
                self._pump(conn)
            while True:
                try:                   # conns workers un-paused since last
                    conn = self._resume.popleft()   # wait (deque is atomic)
                except IndexError:
                    break
                self._pump(conn)
            now = time.monotonic()
            if now - self._last_mirror >= 1.0:
                self._flush_pvars(now)

    def close(self) -> None:
        self._stop.set()
        self._engine.wake()
        self._ready.close()
        with self._conns_lock:
            conns = list(self._conns.values())
        for conn in conns:
            self._close_conn(conn)
        for t in self._threads:
            t.join(timeout=2.0)
        self._engine.close()

    # -- loop side -----------------------------------------------------------
    def _accept_burst(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self.listener.accept()
            except (BlockingIOError, socket.timeout):
                return
            except OSError:
                return                 # listener closed (broker shutdown)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass                   # AF_UNIX
            conn = _Conn(sock, self)
            with self._conns_lock:
                self._conns[conn.fd] = conn
                n = len(self._conns)
                if n > self.peak_sockets:
                    self.peak_sockets = n
            try:
                self._engine.register(conn.fd)
            except OSError:
                self._close_conn(conn)
                continue
            # data may have raced ahead of registration; pump once by hand
            self._pump(conn)

    def _pump(self, conn: _Conn) -> None:
        if conn.dead_read or conn.closed or conn.paused:
            return
        try:
            produced = conn.feed()
        except Exception:
            # Disconnect/SessionError are the expected stream endings, but
            # a hostile frame can blow up the decode itself in ways no
            # enumeration will ever be complete against — and ANY escape
            # here kills the single loop thread for every attached session.
            # Every flavor means the same thing: this stream is done.
            conn.dead_read = True
            conn.frames.append(self._EOF)
            produced = 1
        self.frames_in += produced
        if produced:
            self._ready.push(conn)

    # -- worker side ---------------------------------------------------------
    def _worker(self) -> None:
        while not self._stop.is_set():
            conn = self._ready.pop(timeout=0.5)
            if conn is None:
                continue
            with conn.lock:
                if conn.busy or not conn.frames:
                    continue
                conn.busy = True
                frame = conn.frames.popleft()
            with self._conns_lock:
                self._busy += 1
            try:
                streaming = self._service(conn, frame)
            except Exception:
                # backstop: _service already maps failures to connection
                # teardown, but a bug (or an exception from the teardown
                # itself) escaping here would kill the pool worker and
                # wedge the conn with busy=True forever — absorb it, drop
                # the one connection, keep the worker.
                streaming = False
                self._drop_conn(conn)
            finally:
                with self._conns_lock:
                    self._busy -= 1
            if not streaming:
                self._release(conn)

    def _drop_conn(self, conn: _Conn) -> None:
        """Best-effort teardown of one session (lease revoked, fd closed);
        never raises — the callers are keep-running paths."""
        if conn.lease is not None:
            try:
                self.broker.revoke_lease(conn.lease, "connection lost",
                                         close_conn=False)
            except Exception:
                pass
        try:
            self._close_conn(conn)
        except Exception:
            pass

    def _release(self, conn: _Conn) -> None:
        """End of one service slice: clear the per-connection busy bit,
        re-enqueue when frames are already waiting, and un-pause the read
        side once the backlog has drained below the high-water mark (the
        loop thread owns the parser, so resuming is a handoff: queue the
        conn and wake the loop)."""
        with conn.lock:
            conn.busy = False
            more = bool(conn.frames) and not conn.closed
            resume = (conn.paused and not conn.closed
                      and len(conn.frames) < _FRAME_HWM)
            if resume:
                conn.paused = False
        if more:
            self._ready.push(conn)
        if resume:
            self._resume.append(conn)
            self._engine.wake()

    def _finish_frame(self, frame: list) -> None:
        """Consume a frame exactly once: null the payload slots in place
        (killing every holder's alias at a stroke) and recycle the lease
        buffers. Safe to call at most once per frame by construction —
        the streaming path takes the frame with it, every other path
        finishes it on the worker."""
        frame[2] = None
        bufs, frame[3] = frame[3], ()
        for buf in bufs:
            self.lease_pool.recycle(buf)

    def _service(self, conn: _Conn, frame) -> bool:
        """Handle ONE parsed frame on a worker; returns True when a
        streaming generation took ownership of the connection (its thread
        will release the busy bit and finish the frame)."""
        broker = self.broker
        if frame is self._EOF:
            if conn.lease is not None:
                broker.revoke_lease(conn.lease, "connection lost",
                                    close_conn=False)
            self._close_conn(conn)
            return False
        kind, meta = frame[0], frame[1]
        handed_off = False
        try:
            if conn.lease is None:
                self._service_preattach(conn, kind, meta)
                return False
            lease = conn.lease
            if kind == protocol.DETACH:
                broker.revoke_lease(lease, "client detached",
                                    close_conn=False)
                protocol.send_frame(conn.proxy, protocol.BYE,
                                    {"tenant": lease.tenant})
                self._close_conn(conn)
                return False
            if kind == protocol.PING:
                with lease.send_lock:
                    protocol.send_frame(conn.proxy, protocol.PONG, {})
                return False
            if kind == protocol.STATS:
                with lease.send_lock:
                    protocol.send_frame(conn.proxy, protocol.STATS,
                                        broker.stats())
                return False
            if kind == protocol.METRICS:
                from .. import stats as _stats
                text = _stats.to_prometheus(broker.stats())
                with lease.send_lock:
                    protocol.send_frame(conn.proxy, protocol.METRICS,
                                        {"text": text})
                return False
            if kind != protocol.OP:
                raise SessionError(
                    f"unexpected {protocol.KIND_NAMES.get(kind, kind)} "
                    f"frame mid-session")
            if meta.get("op") == "generate":
                t = threading.Thread(target=self._stream_generate,
                                     args=(conn, lease, frame),
                                     name="serve-generate", daemon=True)
                t.start()
                handed_off = True
                return True            # the stream thread releases busy
            broker._serve_op(lease, meta, frame[2])
            return False
        except Exception:
            # the legacy thread's teardown semantics, exactly: Disconnect/
            # SessionError/OSError are the expected endings, and any other
            # client-triggered exception (non-numeric cid/nranks, etc.)
            # costs that client its connection — never a pool worker.
            self._drop_conn(conn)
            return False
        finally:
            if not handed_off:
                self._finish_frame(frame)

    def _service_preattach(self, conn: _Conn, kind: int, meta: dict) -> None:
        broker = self.broker
        if kind == protocol.STATS:
            # lease-less admin probe (tpurun --serve --stats)
            try:
                broker._check_token(meta.get("token"))
                protocol.send_frame(conn.proxy, protocol.STATS,
                                    broker.stats())
            except MPIError as e:
                protocol.send_frame(conn.proxy, protocol.ERROR,
                                    protocol.error_meta(e))
            self._close_conn(conn)
            return
        if kind == protocol.METRICS:
            # lease-less Prometheus scrape off the event loop — a fleet
            # scraper needs no session and costs no listener thread
            try:
                broker._check_token(meta.get("token"))
                from .. import stats as _stats
                protocol.send_frame(conn.proxy, protocol.METRICS,
                                    {"text": _stats.to_prometheus(
                                        broker.stats())})
            except MPIError as e:
                protocol.send_frame(conn.proxy, protocol.ERROR,
                                    protocol.error_meta(e))
            self._close_conn(conn)
            return
        if kind != protocol.HELLO:
            protocol.send_frame(conn.proxy, protocol.ERROR,
                                protocol.error_meta(SessionError(
                                    f"expected HELLO, got "
                                    f"{protocol.KIND_NAMES.get(kind, kind)}")))
            self._close_conn(conn)
            return
        t0 = time.perf_counter()
        try:
            lease = broker.attach_tenant(conn.proxy, meta)
        except Exception as e:
            # typed MPIErrors cross the wire as-is; anything else a hostile
            # HELLO can trigger (non-numeric nranks, bad field types) is
            # the client's malformed request, reported as such
            err = e if isinstance(e, MPIError) else SessionError(
                f"malformed HELLO: {type(e).__name__}: {e}")
            protocol.send_frame(conn.proxy, protocol.ERROR,
                                protocol.error_meta(err))
            self._close_conn(conn)
            return
        attach_us = (time.perf_counter() - t0) * 1e6
        conn.lease = lease
        with self._conns_lock:
            self.attaches += 1
        protocol.send_frame(conn.proxy, protocol.LEASE, {
            "tenant": lease.tenant, "ranks": list(lease.group),
            "cid": lease.root_cid,
            "cid_base": lease.ns.base, "cid_limit": lease.ns.limit,
            "pool": broker.pool.info(), "attach_us": attach_us})

    def _stream_generate(self, conn: _Conn, lease, frame: list) -> None:
        """A streaming generation on its own thread: RESULT frames flow for
        the stream's whole life, so parking a pool worker on it would let
        max-workers concurrent streams starve every other session. Threads
        here scale with concurrent *streams*, not with attached sockets."""
        try:
            self.broker._serve_generate(lease, frame[1], frame[2])
        except Exception:
            self._drop_conn(conn)
        finally:
            self._finish_frame(frame)
            self._release(conn)

    # -- close / teardown ----------------------------------------------------
    def _close_conn(self, conn: _Conn) -> None:
        """The only place a session fd dies: deregister from the readiness
        set BEFORE close so the kernel cannot recycle the fd number into a
        new accept while stale events for the old one are still queued."""
        with self._conns_lock:
            if conn.closed:
                return
            conn.closed = True
            self._conns.pop(conn.fd, None)
        conn.dead_read = True
        try:
            self._engine.unregister(conn.fd)
        except OSError:
            pass
        try:
            conn.sock.close()
        except OSError:
            pass

    # -- observability -------------------------------------------------------
    def _flush_pvars(self, now: float) -> None:
        """Mirror local counters into the process pvar store as deltas (the
        loop owns its counters; pvar dumps and --stats read the mirror)."""
        self._last_mirror = now
        if not perfvars.enabled():
            return
        lp = self.lease_pool.stats()
        with self._conns_lock:
            counts = {"wakeups": self.wakeups, "frames": self.frames_in,
                      "attaches": self.attaches, "lease_hits": lp["hits"],
                      "lease_misses": lp["misses"],
                      "lease_drops": lp["drops"]}
            open_sockets = len(self._conns)
            busy = self._busy
            # delta-vs-mirror and the mirror update must be one atomic
            # step: this runs on the loop thread AND on worker threads
            # (stats() -> broker.stats()), and two callers working from
            # the same baseline would double-count every delta
            deltas = {k: v - self._mirrored.get(k, 0)
                      for k, v in counts.items()}
            deltas = {k: v for k, v in deltas.items() if v}
            if deltas:
                self._mirrored.update(counts)
        if deltas:
            perfvars.note_front_door(**deltas)
        perfvars.set_front_door_gauges(open_sockets=open_sockets,
                                       workers=self.nworkers,
                                       workers_busy=busy)

    def stats(self) -> dict:
        """The front_door block of Broker.stats(): live socket population,
        attach totals, loop wakeups, recv-lease effectiveness, worker-pool
        occupancy."""
        self._flush_pvars(time.monotonic())
        with self._conns_lock:
            open_sockets = len(self._conns)
            busy = self._busy
            attaches = self.attaches
        uptime = max(time.monotonic() - self.started_at, 1e-9)
        return {"engine": self.engine_kind,
                "open_sockets": open_sockets,
                "peak_sockets": self.peak_sockets,
                "attaches": attaches,
                "attach_per_s": attaches / uptime,
                "uptime_s": uptime,
                "wakeups": self.wakeups,
                "frames": self.frames_in,
                "ready_depth": len(self._ready),
                "workers": self.nworkers,
                "workers_busy": busy,
                "recv_lease": self.lease_pool.stats()}
