"""ctypes binding to the native host transport (transport.cc).

Build model mirrors the reference's deps/ stage (deps/build.jl compiles
gen_consts.c with the system compiler at install time): the shared library is
compiled from the vendored C++ source with the system g++ on first use and
cached next to the source; a stale cache (source newer than .so) rebuilds.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "transport.cc")
_LIB = os.path.join(_HERE, "libtpumpi_transport.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def _stale() -> bool:
    return (not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))


def _build() -> None:
    """Compile under an inter-process lock: N launched rank processes may hit
    first-use simultaneously (tpurun --procs); each builds to its own temp
    file and the winner publishes atomically."""
    import fcntl
    import tempfile

    with open(_LIB + ".lock", "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            if not _stale():     # a sibling built it while we waited
                return
            fd, tmp = tempfile.mkstemp(dir=_HERE, suffix=".so")
            os.close(fd)
            cxx = os.environ.get("TPU_MPI_CXX", "g++")
            cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", tmp]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                os.unlink(tmp)
                raise NativeBuildError(
                    f"native transport build failed ({' '.join(cmd)}):\n"
                    f"{proc.stderr}")
            os.replace(tmp, _LIB)
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def load() -> ctypes.CDLL:
    """Load (building if needed) the native transport library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _stale():
            _build()
        lib = ctypes.CDLL(_LIB)
        lib.tm_create.restype = ctypes.c_void_p
        lib.tm_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.tm_port.restype = ctypes.c_int
        lib.tm_port.argtypes = [ctypes.c_void_p]
        lib.tm_set_peers.restype = ctypes.c_int
        lib.tm_set_peers.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tm_grow.restype = ctypes.c_int
        lib.tm_grow.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p]
        lib.tm_send.restype = ctypes.c_int
        lib.tm_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_longlong]
        lib.tm_sendv.restype = ctypes.c_int
        lib.tm_sendv.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_void_p),
                                 ctypes.POINTER(ctypes.c_longlong),
                                 ctypes.c_int]
        lib.tm_recv.restype = ctypes.c_int
        lib.tm_recv.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_longlong,
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.c_int, ctypes.c_int]
        lib.tm_poke.restype = None
        lib.tm_poke.argtypes = [ctypes.c_void_p]
        lib.tm_hb_enable.restype = None
        lib.tm_hb_enable.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tm_peer_age_ms.restype = ctypes.c_longlong
        lib.tm_peer_age_ms.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tm_stop.restype = None
        lib.tm_stop.argtypes = [ctypes.c_void_p]
        lib.tm_destroy.restype = None
        lib.tm_destroy.argtypes = [ctypes.c_void_p]
        # fd engine (serve front door): edge-triggered readiness over
        # session sockets + the kernel splice byte pump
        lib.tmfd_create.restype = ctypes.c_void_p
        lib.tmfd_create.argtypes = []
        lib.tmfd_add.restype = ctypes.c_int
        lib.tmfd_add.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.tmfd_mod.restype = ctypes.c_int
        lib.tmfd_mod.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.tmfd_del.restype = ctypes.c_int
        lib.tmfd_del.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tmfd_wait.restype = ctypes.c_int
        lib.tmfd_wait.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.POINTER(ctypes.c_int),
                                  ctypes.c_int, ctypes.c_int]
        lib.tmfd_wake.restype = None
        lib.tmfd_wake.argtypes = [ctypes.c_void_p]
        lib.tmfd_destroy.restype = None
        lib.tmfd_destroy.argtypes = [ctypes.c_void_p]
        lib.tmfd_splice.restype = ctypes.c_longlong
        lib.tmfd_splice.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                    ctypes.c_int, ctypes.c_longlong]
        _lib = lib
        return lib


class NativeFdEngine:
    """Edge-triggered readiness engine over an open fd population — the
    serve front door's C10k substrate (tmfd_* in transport.cc). Same shape
    as ``select.epoll`` so the two are drop-in interchangeable in
    tpu_mpi/serve/frontdoor.py; registering an fd also flips it nonblocking
    (ET + a blocking read would deadlock the loop).

    Event bits in ``wait`` results: 1 = readable/hangup, 2 = writable.
    A cross-thread :meth:`wake` surfaces as one ``(-1, 0)`` entry."""

    _MAX_EVENTS = 512

    def __init__(self):
        self._lib = load()
        self._h = self._lib.tmfd_create()
        if not self._h:
            raise NativeBuildError("tmfd_create failed (epoll/pipe error)")
        self._fds = (ctypes.c_int * self._MAX_EVENTS)()
        self._evs = (ctypes.c_int * self._MAX_EVENTS)()

    def register(self, fd: int, want_write: bool = False) -> None:
        if self._lib.tmfd_add(self._h, int(fd), 1 if want_write else 0) != 0:
            raise OSError(f"tmfd_add({fd}) failed")

    def modify(self, fd: int, want_write: bool) -> None:
        if self._lib.tmfd_mod(self._h, int(fd), 1 if want_write else 0) != 0:
            raise OSError(f"tmfd_mod({fd}) failed")

    def unregister(self, fd: int) -> None:
        self._lib.tmfd_del(self._h, int(fd))   # best effort: fd may be gone

    def wait(self, timeout: float) -> list[tuple[int, int]]:
        n = self._lib.tmfd_wait(self._h, self._fds, self._evs,
                                self._MAX_EVENTS, int(timeout * 1000))
        if n < 0:
            raise OSError("tmfd_wait failed")
        return [(self._fds[i], self._evs[i]) for i in range(n)]

    def wake(self) -> None:
        if self._h:
            self._lib.tmfd_wake(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.tmfd_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def splice_fd(src_fd: int, dst_fd: int, pipe_rd: int, pipe_wr: int,
              budget: int) -> int:
    """Kernel splice byte pump (router splice mode): move up to ``budget``
    bytes src -> dst through the caller's pipe. Returns bytes moved, 0 on
    clean EOF, -1 when src would block; raises OSError on a hard error."""
    rc = load().tmfd_splice(int(src_fd), int(dst_fd), int(pipe_rd),
                            int(pipe_wr), int(budget))
    if rc == -2:
        raise OSError("tmfd_splice failed")
    return int(rc)


class NativeTransport:
    """Python handle over one rank's native transport endpoint."""

    # Frames at or under this size land in a reusable receive buffer via a
    # SINGLE tm_recv call (no tm_peek round trip, no per-frame allocation)
    # and are copied out; larger frames take the exact-size zero-copy path.
    # 16 KiB (not 4): a 4 KiB payload plus fast-lane header must fit, or the
    # 4 KiB ladder point pays a second FFI round trip and its p50 steps up.
    _RBUF_CAP = 16384

    def __init__(self, rank: int, size: int):
        self._lib = load()
        self._h = self._lib.tm_create(rank, size)
        if not self._h:
            raise NativeBuildError("tm_create failed (socket/bind error)")
        self.rank = rank
        self.size = size
        self._rbuf = None
        self._rbuf_ptr = None

    @property
    def port(self) -> int:
        return self._lib.tm_port(self._h)

    def set_peers(self, addrs: list[str]) -> None:
        csv = ",".join(addrs).encode()
        if self._lib.tm_set_peers(self._h, csv) != 0:
            raise NativeBuildError(f"tm_set_peers rejected {addrs!r}")

    def grow(self, addrs: list[str]) -> None:
        """Extend the world to len(addrs) ranks (MPI_Comm_spawn support);
        the full new address table, existing ranks' slots unchanged."""
        csv = ",".join(addrs).encode()
        if self._lib.tm_grow(self._h, len(addrs), csv) != 0:
            raise NativeBuildError(f"tm_grow rejected {addrs!r}")
        self.size = len(addrs)

    def send(self, dst: int, payload: bytes) -> None:
        rc = self._lib.tm_send(self._h, dst, payload, len(payload))
        if rc != 0:
            raise ConnectionError(f"native send to rank {dst} failed")

    def sendv(self, dst: int, parts: list) -> None:
        """Scatter-gather send: the frame body is the concatenation of
        ``parts`` (bytes / memoryview / numpy buffers), written with writev —
        array payloads go from their own memory to the socket with no join
        copy (the zero-copy half of the OOB wire codec).

        Small frames are JOINED and sent as one buffer instead: the join
        copy of a few hundred bytes is far cheaper than the per-part
        numpy/ctypes marshalling writev needs (the small-message latency
        path, VERDICT r3 #4)."""
        import numpy as np
        n = len(parts)
        if n > 1:
            total = 0
            for q in parts:
                total += q.nbytes if hasattr(q, "nbytes") else len(q)
                if total > self._RBUF_CAP:
                    break
            if total <= self._RBUF_CAP:
                self.send(dst, b"".join(
                    q.tobytes() if isinstance(q, np.ndarray) else bytes(q)
                    for q in parts))
                return
        views = [np.frombuffer(p, np.uint8) for p in parts]
        bufs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
        lens = (ctypes.c_longlong * n)(*[v.nbytes for v in views])
        rc = self._lib.tm_sendv(self._h, dst, bufs, lens, n)
        if rc != 0:
            raise ConnectionError(f"native sendv to rank {dst} failed")

    def recv(self, timeout_ms: int,
             direct: bool = False) -> Optional[tuple[int, memoryview]]:
        """(src, payload view) or None on timeout. Raises on shutdown.

        Small frames: ONE tm_recv into a reusable buffer, copied out
        (the copy of <=4 KB is cheaper than a second FFI round trip plus a
        fresh allocation — the small-message latency path, VERDICT r2
        weak #4). Large frames: exact-size allocation, zero-copy — array
        payloads decoded by ``backend.loads_oob`` alias the buffer
        directly.

        ``direct=True`` (blocked-receiver drain, VERDICT r3 #4): the calling
        thread runs the C++ poll/read engine inline instead of waiting on
        the inbox condition variable — the sender's bytes wake THIS thread
        straight out of poll(), skipping both the progress-thread and
        cv hand-offs. The C++ progress thread parks while direct receives
        are active/recent."""
        import numpy as np  # local: keep module import light for launcher
        rb = self._rbuf
        if rb is None:
            rb = self._rbuf = np.empty(self._RBUF_CAP, np.uint8)
            # one ctypes cast for the life of the endpoint: data_as() builds
            # a fresh c_void_p per call, measurable on the latency path
            self._rbuf_ptr = rb.ctypes.data_as(ctypes.c_void_p)
        src = ctypes.c_int()
        length = ctypes.c_longlong()
        rc = self._lib.tm_recv(self._h, self._rbuf_ptr,
                               self._RBUF_CAP, ctypes.byref(src),
                               ctypes.byref(length), timeout_ms,
                               1 if direct else 0)
        if rc == 1:
            return None
        if rc == -3:
            # frame larger than the reusable buffer (kept in the queue):
            # pop it into an exact-size buffer, returned zero-copy
            arr = np.empty(int(length.value), np.uint8)
            rc = self._lib.tm_recv(self._h,
                                   arr.ctypes.data_as(ctypes.c_void_p),
                                   length.value, ctypes.byref(src),
                                   ctypes.byref(length), timeout_ms, 0)
            if rc == -2:
                raise ConnectionResetError("transport stopped")
            if rc != 0:
                return None
            return src.value, memoryview(arr)[: length.value]
        if rc == -2:
            raise ConnectionResetError("transport stopped")
        if rc != 0:
            return None
        # reusable buffer: copy out before the next recv clobbers it.
        # bytearray, not bytes: zero-copy array views decoded over this
        # frame must stay WRITABLE like the exact-size path's np.empty
        # buffer (MPI-style in-place ops mutate received contributions)
        return src.value, memoryview(bytearray(rb[: length.value]))

    def poke(self) -> None:
        """Ask a non-direct recv holder (the drainer) to yield its lease."""
        if self._h:
            self._lib.tm_poke(self._h)

    def hb_enable(self, interval_ms: int) -> None:
        """Turn on heartbeat emission + liveness tracking (0 turns it off).
        Every peer starts 'heard now' — the silence clock begins here."""
        if self._h:
            self._lib.tm_hb_enable(self._h, int(interval_ms))

    def peer_age_ms(self, peer: int) -> int:
        """ms since ``peer`` was last heard; -1 detection off / unknown,
        -2 peer known dead (closed socket or refused heartbeat)."""
        if not self._h:
            return -1
        return int(self._lib.tm_peer_age_ms(self._h, int(peer)))

    def stop(self) -> None:
        if self._h:
            self._lib.tm_stop(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.tm_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
