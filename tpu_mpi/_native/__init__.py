"""ctypes binding to the native host transport (transport.cc).

Build model mirrors the reference's deps/ stage (deps/build.jl compiles
gen_consts.c with the system compiler at install time): the shared library is
compiled from the vendored C++ source with the system g++ on first use and
cached next to the source; a stale cache (source newer than .so) rebuilds.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "transport.cc")
_LIB = os.path.join(_HERE, "libtpumpi_transport.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class NativeBuildError(RuntimeError):
    pass


def _stale() -> bool:
    return (not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))


def _build() -> None:
    """Compile under an inter-process lock: N launched rank processes may hit
    first-use simultaneously (tpurun --procs); each builds to its own temp
    file and the winner publishes atomically."""
    import fcntl
    import tempfile

    with open(_LIB + ".lock", "w") as lf:
        fcntl.flock(lf, fcntl.LOCK_EX)
        try:
            if not _stale():     # a sibling built it while we waited
                return
            fd, tmp = tempfile.mkstemp(dir=_HERE, suffix=".so")
            os.close(fd)
            cxx = os.environ.get("TPU_MPI_CXX", "g++")
            cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
                   _SRC, "-o", tmp]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode != 0:
                os.unlink(tmp)
                raise NativeBuildError(
                    f"native transport build failed ({' '.join(cmd)}):\n"
                    f"{proc.stderr}")
            os.replace(tmp, _LIB)
        finally:
            fcntl.flock(lf, fcntl.LOCK_UN)


def load() -> ctypes.CDLL:
    """Load (building if needed) the native transport library."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _stale():
            _build()
        lib = ctypes.CDLL(_LIB)
        lib.tm_create.restype = ctypes.c_void_p
        lib.tm_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.tm_port.restype = ctypes.c_int
        lib.tm_port.argtypes = [ctypes.c_void_p]
        lib.tm_set_peers.restype = ctypes.c_int
        lib.tm_set_peers.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.tm_grow.restype = ctypes.c_int
        lib.tm_grow.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p]
        lib.tm_send.restype = ctypes.c_int
        lib.tm_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.c_void_p, ctypes.c_longlong]
        lib.tm_sendv.restype = ctypes.c_int
        lib.tm_sendv.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_void_p),
                                 ctypes.POINTER(ctypes.c_longlong),
                                 ctypes.c_int]
        lib.tm_peek.restype = ctypes.c_longlong
        lib.tm_peek.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.tm_recv.restype = ctypes.c_int
        lib.tm_recv.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_longlong,
                                ctypes.POINTER(ctypes.c_int),
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.c_int]
        lib.tm_stop.restype = None
        lib.tm_stop.argtypes = [ctypes.c_void_p]
        lib.tm_destroy.restype = None
        lib.tm_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class NativeTransport:
    """Python handle over one rank's native transport endpoint."""

    def __init__(self, rank: int, size: int):
        self._lib = load()
        self._h = self._lib.tm_create(rank, size)
        if not self._h:
            raise NativeBuildError("tm_create failed (socket/bind error)")
        self.rank = rank
        self.size = size

    @property
    def port(self) -> int:
        return self._lib.tm_port(self._h)

    def set_peers(self, addrs: list[str]) -> None:
        csv = ",".join(addrs).encode()
        if self._lib.tm_set_peers(self._h, csv) != 0:
            raise NativeBuildError(f"tm_set_peers rejected {addrs!r}")

    def grow(self, addrs: list[str]) -> None:
        """Extend the world to len(addrs) ranks (MPI_Comm_spawn support);
        the full new address table, existing ranks' slots unchanged."""
        csv = ",".join(addrs).encode()
        if self._lib.tm_grow(self._h, len(addrs), csv) != 0:
            raise NativeBuildError(f"tm_grow rejected {addrs!r}")
        self.size = len(addrs)

    def send(self, dst: int, payload: bytes) -> None:
        rc = self._lib.tm_send(self._h, dst, payload, len(payload))
        if rc != 0:
            raise ConnectionError(f"native send to rank {dst} failed")

    def sendv(self, dst: int, parts: list) -> None:
        """Scatter-gather send: the frame body is the concatenation of
        ``parts`` (bytes / memoryview / numpy buffers), written with writev —
        array payloads go from their own memory to the socket with no join
        copy (the zero-copy half of the OOB wire codec)."""
        import numpy as np
        n = len(parts)
        views = [np.frombuffer(p, np.uint8) for p in parts]
        bufs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
        lens = (ctypes.c_longlong * n)(*[v.nbytes for v in views])
        rc = self._lib.tm_sendv(self._h, dst, bufs, lens, n)
        if rc != 0:
            raise ConnectionError(f"native sendv to rank {dst} failed")

    def recv(self, timeout_ms: int) -> Optional[tuple[int, memoryview]]:
        """(src, payload view) or None on timeout. Raises on shutdown.

        The payload is a memoryview over a fresh non-zeroed buffer — no
        extra Python-side copies; array payloads decoded by
        ``backend.loads_oob`` alias it directly."""
        import numpy as np  # local: keep module import light for launcher
        n = self._lib.tm_peek(self._h, timeout_ms)
        if n == -1:
            return None
        if n == -2:
            raise ConnectionResetError("transport stopped")
        arr = np.empty(int(n), np.uint8)          # no zero-fill (hot path)
        src = ctypes.c_int()
        length = ctypes.c_longlong()
        rc = self._lib.tm_recv(self._h, arr.ctypes.data_as(ctypes.c_void_p),
                               n, ctypes.byref(src), ctypes.byref(length),
                               timeout_ms)
        if rc == 1:
            return None
        if rc == -3:
            # a larger frame arrived between peek and recv; retry with its size
            arr = np.empty(int(length.value), np.uint8)
            rc = self._lib.tm_recv(self._h,
                                   arr.ctypes.data_as(ctypes.c_void_p),
                                   length.value, ctypes.byref(src),
                                   ctypes.byref(length), timeout_ms)
        if rc == -2:
            raise ConnectionResetError("transport stopped")
        if rc != 0:
            return None
        return src.value, memoryview(arr)[: length.value]

    def stop(self) -> None:
        if self._h:
            self._lib.tm_stop(self._h)

    def close(self) -> None:
        if self._h:
            self._lib.tm_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
