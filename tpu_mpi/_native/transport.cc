// tpu_mpi native host transport: framed TCP messaging with a poll()-based
// progress engine.
//
// This is the DCN-tier native component (SURVEY.md §2.4): the reference links
// an external C libmpi whose progress engine moves bytes between OS
// processes; here the equivalent engine is built in, reached from Python via
// ctypes. Scope is deliberately the *transport*: reliable framed delivery
// between ranks with a background progress thread and a blocking inbox.
// Message semantics (tags, wildcards, probe, collective rendezvous) live in
// the Python object model above, exactly as the reference keeps its object
// model in Julia above libmpi's byte engine.
//
// Wire format per frame: [u32 magic][i32 src][i64 len][payload bytes].
// TCP gives per-peer FIFO; the single progress thread preserves arrival
// order into one inbox, so MPI's non-overtaking guarantee holds per (src,dst).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x7D5A11E7u;
// Heartbeat frames (fault detection, docs/fault-tolerance.md): header-only
// frames under a second magic so they never enter the inbox — the liveness
// plane shares the data plane's sockets but not its delivery queue.
constexpr uint32_t kHbMagic = 0x7D5AFEEDu;

// Corrupt-stream guard: a garbled-but-magic-valid header must not make the
// connection buffer grow unboundedly waiting for bytes that never arrive.
// Overridable via TPU_MPI_MAX_FRAME_BYTES (see tpu_mpi.config); default 2 GiB.
int64_t max_frame_bytes() {
  static int64_t cached = [] {
    const char* s = ::getenv("TPU_MPI_MAX_FRAME_BYTES");
    if (s != nullptr) {
      char* end = nullptr;
      long long v = strtoll(s, &end, 10);
      if (end != s && v > 0) return static_cast<int64_t>(v);
    }
    return static_cast<int64_t>(1) << 31;
  }();
  return cached;
}

struct FrameHeader {
  uint32_t magic;
  int32_t src;
  int64_t len;
} __attribute__((packed));

struct Frame {
  int32_t src = -1;
  std::unique_ptr<uint8_t[]> data;  // new uint8_t[n]: no zero-init (hot path)
  size_t len = 0;
};

// Per-connection incremental read state: the header accumulates in a small
// staging vector; the body is read DIRECTLY into the frame's final buffer
// (no intermediate parse buffer, no re-copy — the bandwidth-critical path).
struct Conn {
  int fd = -1;
  std::vector<uint8_t> hdr;  // partial header bytes (< sizeof(FrameHeader))
  Frame cur;                 // in-progress frame (body being filled)
  size_t filled = 0;         // bytes of cur.data received so far
  bool in_body = false;
  int src_hint = -1;         // last src seen on this conn (death attribution)
};

bool write_all(int fd, const void* p, size_t n) {
  const uint8_t* b = static_cast<const uint8_t*>(p);
  while (n > 0) {
    ssize_t w = ::send(fd, b, n, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    b += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

// writev with partial-write resumption (iov is clobbered).
bool writev_all(int fd, iovec* iov, size_t niov) {
  size_t i = 0;
  while (i < niov) {
    msghdr msg{};
    msg.msg_iov = iov + i;
    msg.msg_iovlen = niov - i;
    ssize_t w = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
    if (w < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    size_t left = static_cast<size_t>(w);
    while (i < niov && left >= iov[i].iov_len) {
      left -= iov[i].iov_len;
      ++i;
    }
    if (i < niov && left > 0) {
      iov[i].iov_base = static_cast<uint8_t*>(iov[i].iov_base) + left;
      iov[i].iov_len -= left;
    }
  }
  return true;
}

class Transport {
 public:
  Transport(int rank, int size) : rank_(rank), size_(size) {
    peer_fds_.assign(size, -1);
    for (int i = 0; i < size; ++i) peer_locks_.emplace_back();
  }

  ~Transport() { stop(); }

  bool listen_any() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = 0;
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (::listen(listen_fd_, size_.load() + 8) < 0) return false;
    socklen_t alen = sizeof(addr);
    if (::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &alen) < 0)
      return false;
    port_ = ntohs(addr.sin_port);
    if (::pipe(wake_pipe_) != 0) return false;
    ::fcntl(wake_pipe_[0], F_SETFL, O_NONBLOCK);
    progress_ = std::thread([this] { progress_loop(); });
    return true;
  }

  int port() const { return port_; }

  static std::vector<std::string> parse_csv(const std::string& csv) {
    std::vector<std::string> out;
    size_t pos = 0;
    while (pos <= csv.size()) {
      size_t comma = csv.find(',', pos);
      if (comma == std::string::npos) comma = csv.size();
      out.push_back(csv.substr(pos, comma - pos));
      pos = comma + 1;
    }
    return out;
  }

  // csv: "host:port,host:port,..." indexed by rank.
  bool set_peers(const std::string& csv) {
    std::lock_guard<std::mutex> g(peers_mtx_);
    peer_addrs_ = parse_csv(csv);
    return static_cast<int>(peer_addrs_.size()) == size_.load();
  }

  // Extend the world in place (dynamic process management, MPI_Comm_spawn):
  // the csv is the FULL new address table. Existing ranks' slots keep their
  // addresses (deque element references are stable across push_back);
  // concurrent indexers fetch their slot pointers under peers_mtx_ (see
  // slot_for), so the deque's internal bookkeeping is never raced; size_
  // publishes last so a send to a new rank only passes the bounds check
  // once its slot exists.
  bool grow(int new_size, const std::string& csv) {
    std::lock_guard<std::mutex> g(peers_mtx_);
    if (new_size < size_.load()) return false;
    std::vector<std::string> addrs = parse_csv(csv);
    if (static_cast<int>(addrs.size()) != new_size) return false;
    peer_addrs_ = std::move(addrs);
    while (static_cast<int>(peer_fds_.size()) < new_size) {
      peer_fds_.push_back(-1);
      peer_locks_.emplace_back();
    }
    if (hb_enabled_.load(std::memory_order_relaxed)) {
      std::lock_guard<std::mutex> hg(hb_mtx_);
      while (static_cast<int>(last_heard_us_.size()) < new_size) {
        last_heard_us_.push_back(now_us());
        peer_dead_.push_back(0);
      }
    }
    size_.store(new_size);
    return true;
  }

  // Blocking framed send. Thread-safe per destination.
  bool send(int dst, const void* buf, int64_t len) {
    const void* bufs[1] = {buf};
    int64_t lens[1] = {len};
    return sendv(dst, bufs, lens, 1);
  }

  // Scatter-gather framed send: the frame body is the concatenation of the
  // given buffers, written with writev — no join copy on the send path (the
  // Python codec hands the pickle skeleton and each array buffer separately).
  bool sendv(int dst, const void** bufs, const int64_t* lens, int nbufs) {
    if (dst < 0 || dst >= size_.load() || stopped_.load() || nbufs < 0)
      return false;
    int64_t total = 0;
    for (int i = 0; i < nbufs; ++i) total += lens[i];
    if (dst == rank_) {  // self-send: straight to the inbox
      Frame f;
      f.src = rank_;
      f.len = static_cast<size_t>(total);
      f.data.reset(new uint8_t[f.len]);
      size_t off = 0;
      for (int i = 0; i < nbufs; ++i) {
        memcpy(f.data.get() + off, bufs[i], static_cast<size_t>(lens[i]));
        off += static_cast<size_t>(lens[i]);
      }
      push_frame(std::move(f));
      return true;
    }
    std::mutex* plk;
    int* fd_slot;
    {
      // Deque operator[] walks internal bookkeeping that a concurrent
      // grow() push_back mutates; fetch the slot pointers under peers_mtx_
      // (the references themselves stay valid after unlock).
      std::lock_guard<std::mutex> g(peers_mtx_);
      plk = &peer_locks_[dst];
      fd_slot = &peer_fds_[dst];
    }
    std::lock_guard<std::mutex> g(*plk);
    int fd = *fd_slot;
    if (fd < 0) {
      fd = connect_peer(dst);
      if (fd < 0) return false;
      *fd_slot = fd;
    }
    FrameHeader h{kMagic, rank_, total};
    std::vector<iovec> iov;
    iov.reserve(static_cast<size_t>(nbufs) + 1);
    iov.push_back({&h, sizeof(h)});
    for (int i = 0; i < nbufs; ++i)
      if (lens[i] > 0)
        iov.push_back({const_cast<void*>(bufs[i]),
                       static_cast<size_t>(lens[i])});
    if (!writev_all(fd, iov.data(), iov.size())) {
      ::close(fd);
      *fd_slot = -1;
      return false;
    }
    return true;
  }

  static int64_t now_us() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  // Busy phase before blocking: when traffic is hot (a frame arrived within
  // the last 2 ms), the next frame is overwhelmingly likely to be imminent —
  // spinning ~60 us dodges the condition-variable wake (10-20 us scheduler
  // latency) on exactly the ping-pong pattern that dominates small-message
  // latency (OSU-style). Idle consumers fall through to the cv wait at once,
  // so the drainer's duty cycle stays negligible.
  //
  // Spinning REQUIRES spare cores: on a 1-2 core host the spinner burns the
  // timeslice the producing thread needs and latency gets WORSE (measured
  // 99 -> 176 us on a 1-core box). Enabled only with >= 4 hardware threads;
  // TPU_MPI_SPIN_US overrides the window (0 disables).
  static int spin_us() {
    static const int v = [] {
      if (const char* e = ::getenv("TPU_MPI_SPIN_US")) return ::atoi(e);
      return std::thread::hardware_concurrency() >= 4 ? 60 : 0;
    }();
    return v;
  }

  void hot_spin() {
    const int window = spin_us();
    if (window <= 0) return;
    if (inbox_n_.load(std::memory_order_acquire) > 0 || stopped_.load())
      return;
    if (now_us() - last_push_us_.load(std::memory_order_relaxed) > 2000)
      return;
    int64_t deadline = now_us() + window;
    while (now_us() < deadline) {
      if (inbox_n_.load(std::memory_order_acquire) > 0 || stopped_.load())
        return;
#if defined(__x86_64__) || defined(__i386__)
      __builtin_ia32_pause();
#endif
    }
  }

  // Pop the front frame (q_mtx_ held): 0 ok, 1 empty, -3 cap too small
  // (frame kept for an exact-size retry).
  int try_pop_locked(void* buf, int64_t cap, int32_t* src_out,
                     int64_t* len_out) {
    if (inbox_.empty()) return 1;
    Frame& f = inbox_.front();
    *len_out = static_cast<int64_t>(f.len);
    *src_out = f.src;
    if (cap < *len_out) return -3;
    memcpy(buf, f.data.get(), f.len);
    inbox_.pop_front();
    inbox_n_.fetch_sub(1, std::memory_order_release);
    return 0;
  }

  // Pop into buf. 0 ok, 1 timeout, -2 stopped, -3 cap too small (frame kept).
  //
  // direct=false: wait on the inbox condition variable for the progress
  // thread's push (two thread hand-offs per message).
  //
  // direct=true (VERDICT r3 #4, blocked-receiver direct drain): this thread
  // takes the io lease and runs the poll/read engine INLINE — the sender's
  // bytes wake this thread straight out of poll(), no progress-thread or
  // cv hop. The progress thread parks itself while direct receives are
  // active or recent (direct_hot), so the two never fight for the core.
  int recv(void* buf, int64_t cap, int32_t* src_out, int64_t* len_out,
           int timeout_ms, bool direct) {
    if (!direct) hot_spin();
    const int64_t deadline = now_us() + static_cast<int64_t>(timeout_ms) * 1000;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(q_mtx_);
        int rc = try_pop_locked(buf, cap, src_out, len_out);
        if (rc != 1) return rc;
        if (stopped_.load()) return -2;
        if (!direct) {
          int64_t rem_ms = (deadline - now_us()) / 1000;
          if (rem_ms <= 0) return 1;
          // Yields early (as a timeout) when asked: the Python drainer
          // holds its pump lease across this wait, and a direct receiver
          // must be able to take that lease in microseconds, not after our
          // full slice. The ask arrives via tm_poke (the Python layer's
          // lock excludes reaching recv(direct) while we hold the lease,
          // so the direct_waiters_ count alone cannot signal it).
          q_cv_.wait_for(lk, std::chrono::milliseconds(rem_ms), [this] {
            return !inbox_.empty() || stopped_.load() ||
                   direct_waiters_.load(std::memory_order_relaxed) > 0 ||
                   yield_req_.load(std::memory_order_relaxed) > 0;
          });
          if (inbox_.empty() && !stopped_.load()) {
            yield_req_.store(0, std::memory_order_relaxed);
            return 1;
          }
          continue;                    // loop pops under the lock
        }
      }
      // -- direct drive ----------------------------------------------------
      direct_waiters_.fetch_add(1, std::memory_order_relaxed);
      if (io_mtx_.try_lock()) {
        int64_t rem_ms = (deadline - now_us()) / 1000;
        int slice = rem_ms < 1 ? 1 : (rem_ms > 50 ? 50 : static_cast<int>(rem_ms));
        pump_io(slice);
        io_mtx_.unlock();
      } else {
        // the progress thread holds the engine: poke its poll and yield any
        // non-direct cv waiter, then wait briefly for it to hand over.
        // (Poking only on THIS path matters: an unconditional poke would
        // make our own next poll wake instantly on the stale pipe byte —
        // a busy spin that starves the sender process on small-core hosts.)
        poke_wake();
        q_cv_.notify_all();
        std::unique_lock<std::mutex> lk(q_mtx_);
        q_cv_.wait_for(lk, std::chrono::milliseconds(1), [this] {
          return !inbox_.empty() || stopped_.load();
        });
      }
      last_direct_us_.store(now_us(), std::memory_order_relaxed);
      direct_waiters_.fetch_sub(1, std::memory_order_relaxed);
      if (now_us() >= deadline &&
          inbox_n_.load(std::memory_order_acquire) == 0)
        return 1;
    }
  }

  // -- failure detection (heartbeats + closed-socket attribution) -----------
  // Enable liveness tracking: every peer starts "heard now" (grace from
  // enable time), heartbeats go out every interval_ms from whichever thread
  // drives pump_io. interval_ms <= 0 turns the whole plane back off.
  void hb_enable(int interval_ms) {
    std::lock_guard<std::mutex> g(hb_mtx_);
    int n = size_.load();
    last_heard_us_.assign(n, now_us());
    peer_dead_.assign(n, 0);
    hb_interval_ms_.store(interval_ms, std::memory_order_relaxed);
    hb_enabled_.store(interval_ms > 0, std::memory_order_relaxed);
  }

  // Milliseconds since the peer was last heard (any frame counts as
  // liveness); -1 when detection is off / rank out of range, -2 when the
  // peer is known dead (socket closed or heartbeat send refused).
  long long peer_age_ms(int peer) {
    if (!hb_enabled_.load(std::memory_order_relaxed)) return -1;
    std::lock_guard<std::mutex> g(hb_mtx_);
    if (peer < 0 || peer >= static_cast<int>(last_heard_us_.size())) return -1;
    if (peer_dead_[peer]) return -2;
    return (now_us() - last_heard_us_[peer]) / 1000;
  }

  // Ask any thread blocked in a NON-direct recv (the Python drainer) to
  // yield its lease immediately; also breaks the progress thread's poll.
  void request_yield() {
    yield_req_.fetch_add(1, std::memory_order_relaxed);
    poke_wake();
    q_cv_.notify_all();
  }

  void stop() {
    bool was = stopped_.exchange(true);
    if (was) return;
    q_cv_.notify_all();
    if (wake_pipe_[1] >= 0) {
      char c = 'x';
      (void)!::write(wake_pipe_[1], &c, 1);
    }
    if (progress_.joinable()) progress_.join();
    // A rank thread may still be inside pump_io() (direct drain); the
    // stopped_ flag + wake poke make it return promptly, and holding the
    // io lease below means we never close fds out from under it.
    std::lock_guard<std::mutex> io_g(io_mtx_);
    int npeers;
    {
      std::lock_guard<std::mutex> g(peers_mtx_);
      npeers = static_cast<int>(peer_fds_.size());
    }
    for (int i = 0; i < npeers; ++i) {
      std::mutex* plk;
      int* fd_slot;
      {
        // slot pointers fetched under peers_mtx_ (concurrent grow safety,
        // same discipline as sendv)
        std::lock_guard<std::mutex> g(peers_mtx_);
        plk = &peer_locks_[i];
        fd_slot = &peer_fds_[i];
      }
      // Lock out concurrent send(): closing under a live write_all would
      // hand the fd number back to the OS for reuse mid-write.
      std::lock_guard<std::mutex> g(*plk);
      if (*fd_slot >= 0) {
        ::close(*fd_slot);
        *fd_slot = -1;
      }
    }
    for (Conn& c : conns_)
      if (c.fd >= 0) ::close(c.fd);
    conns_.clear();
    if (listen_fd_ >= 0) ::close(listen_fd_);
    listen_fd_ = -1;
    for (int i = 0; i < 2; ++i)
      if (wake_pipe_[i] >= 0) {
        ::close(wake_pipe_[i]);
        wake_pipe_[i] = -1;
      }
  }

 private:
  int connect_peer(int dst) {
    std::string addr;
    {
      std::lock_guard<std::mutex> g(peers_mtx_);
      if (dst >= static_cast<int>(peer_addrs_.size())) return -1;
      addr = peer_addrs_[dst];
    }
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) return -1;
    std::string host = addr.substr(0, colon);
    std::string port = addr.substr(colon + 1);
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    if (::getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0 || !res)
      return -1;
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0 && ::connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      ::close(fd);
      fd = -1;
    }
    ::freeaddrinfo(res);
    if (fd >= 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      int bufsz = 4 << 20;
      ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bufsz, sizeof(bufsz));
    }
    return fd;
  }

  void push_frame(Frame&& f) {
    {
      std::lock_guard<std::mutex> g(q_mtx_);
      inbox_.push_back(std::move(f));
    }
    last_push_us_.store(now_us(), std::memory_order_relaxed);
    inbox_n_.fetch_add(1, std::memory_order_release);
    q_cv_.notify_all();
  }

  void poke_wake() {
    if (wake_pipe_[1] >= 0) {
      char c = 'w';
      (void)!::write(wake_pipe_[1], &c, 1);
    }
  }

  void note_heard(int src) {
    if (!hb_enabled_.load(std::memory_order_relaxed)) return;
    std::lock_guard<std::mutex> g(hb_mtx_);
    if (src >= 0 && src < static_cast<int>(last_heard_us_.size()))
      last_heard_us_[src] = now_us();
  }

  void mark_dead(int src) {
    if (!hb_enabled_.load(std::memory_order_relaxed)) return;
    std::lock_guard<std::mutex> g(hb_mtx_);
    if (src >= 0 && src < static_cast<int>(peer_dead_.size()))
      peer_dead_[src] = 1;
  }

  // Emit one heartbeat header to every live peer when the interval elapsed.
  // Runs under io_mtx_ (top of pump_io) so it fires no matter which thread
  // — the progress thread or a direct receiver — currently drives the
  // engine. Per-peer locks are only try_lock'd: a rank thread mid-send IS
  // liveness traffic, skipping is correct. Sends use MSG_DONTWAIT — a
  // backed-up socket must not wedge the io engine; EAGAIN just skips this
  // beat (the peer isn't reading, the age check will say so). A refused
  // connect or a hard send error marks the peer dead immediately: on a
  // SIGKILLed peer that is the fast path, far ahead of the silence timeout.
  void maybe_send_heartbeats() {
    if (!hb_enabled_.load(std::memory_order_relaxed)) return;
    int64_t interval_us =
        static_cast<int64_t>(hb_interval_ms_.load(std::memory_order_relaxed)) *
        1000;
    int64_t now = now_us();
    if (now - hb_last_sent_us_.load(std::memory_order_relaxed) < interval_us)
      return;
    hb_last_sent_us_.store(now, std::memory_order_relaxed);
    int n = size_.load();
    for (int dst = 0; dst < n; ++dst) {
      if (dst == rank_) continue;
      {
        std::lock_guard<std::mutex> g(hb_mtx_);
        if (dst < static_cast<int>(peer_dead_.size()) && peer_dead_[dst])
          continue;
      }
      std::mutex* plk;
      int* fd_slot;
      {
        std::lock_guard<std::mutex> g(peers_mtx_);
        if (dst >= static_cast<int>(peer_fds_.size())) continue;
        plk = &peer_locks_[dst];
        fd_slot = &peer_fds_[dst];
      }
      if (!plk->try_lock()) continue;
      int fd = *fd_slot;
      if (fd < 0) {
        fd = connect_peer(dst);
        if (fd < 0) {
          plk->unlock();
          mark_dead(dst);
          continue;
        }
        *fd_slot = fd;
      }
      FrameHeader h{kHbMagic, rank_, 0};
      ssize_t w = ::send(fd, &h, sizeof(h), MSG_NOSIGNAL | MSG_DONTWAIT);
      bool ok = true;
      if (w == sizeof(h)) {
      } else if (w < 0) {
        ok = (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR);
      } else {
        // Partial header write (socket buffer brim-full at exactly the
        // wrong byte): the stream is committed — finish it blocking, the
        // remainder is < 16 bytes. A dead peer fails this fast (RST).
        ok = write_all(fd, reinterpret_cast<const uint8_t*>(&h) + w,
                       sizeof(h) - static_cast<size_t>(w));
      }
      if (!ok) {
        ::close(fd);
        *fd_slot = -1;
        plk->unlock();
        mark_dead(dst);
        continue;
      }
      plk->unlock();
    }
  }

  bool direct_hot() const {
    return direct_waiters_.load(std::memory_order_relaxed) > 0 ||
           now_us() - last_direct_us_.load(std::memory_order_relaxed) < 20000;
  }

  void progress_loop() {
    while (!stopped_.load()) {
      if (direct_hot()) {
        // a receiver thread is (or was a moment ago) driving the io engine
        // inline; staying off the sockets lets it wake directly on arrival
        // instead of waiting out our poll slice
        std::this_thread::sleep_for(std::chrono::milliseconds(5));
        continue;
      }
      std::lock_guard<std::mutex> g(io_mtx_);
      pump_io(200);
    }
  }

  // One poll/accept/read cycle over the listen socket, wake pipe and all
  // connections (io_mtx_ held by the caller: the progress thread or a
  // direct-receiving rank thread).
  void pump_io(int timeout_ms) {
    if (hb_enabled_.load(std::memory_order_relaxed)) {
      maybe_send_heartbeats();
      // the poll slice must not outlive the heartbeat period, or beats
      // stall behind an idle 200 ms progress-thread poll
      int iv = hb_interval_ms_.load(std::memory_order_relaxed);
      if (iv > 0 && timeout_ms > iv) timeout_ms = iv;
    }
    {
      std::vector<pollfd> pfds;
      pfds.push_back({listen_fd_, POLLIN, 0});
      pfds.push_back({wake_pipe_[0], POLLIN, 0});
      for (Conn& c : conns_) pfds.push_back({c.fd, POLLIN, 0});
      int rc = ::poll(pfds.data(), pfds.size(), timeout_ms);
      if (stopped_.load()) return;
      if (rc <= 0) return;
      if (pfds[0].revents & POLLIN) {
        int fd = ::accept(listen_fd_, nullptr, nullptr);
        if (fd >= 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          int bufsz = 4 << 20;
          ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bufsz, sizeof(bufsz));
          // Non-blocking so the drain loop below can read to exhaustion
          // without risking a stall on an exactly-slab-sized burst.
          ::fcntl(fd, F_SETFL, ::fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
          conns_.push_back(Conn{fd, {}});
        }
      }
      if (pfds[1].revents & POLLIN) {
        char tmp[64];
        while (::read(wake_pipe_[0], tmp, sizeof(tmp)) > 0) {
        }
      }
      for (size_t i = 2; i < pfds.size(); ++i) {
        if (!(pfds[i].revents & (POLLIN | POLLHUP | POLLERR))) continue;
        Conn& c = conns_[i - 2];
        // Drain the (non-blocking) socket to exhaustion: headers accumulate
        // in a small staging vector, bodies stream in large slabs directly
        // into the frame's final buffer — one copy total on the receive
        // path. (Round 1 read 64 KiB per poll() cycle through a growing
        // parse buffer: ~1 GB/s on loopback; this path removes both the
        // syscall-per-64KiB and the re-copy.) A per-cycle byte cap keeps
        // multi-peer fairness.
        constexpr size_t kReadSlab = 4 << 20;
        constexpr size_t kMaxPerCycle = 64 << 20;
        bool dead = false;
        size_t cycle = 0;
        while (cycle < kMaxPerCycle) {
          ssize_t r;
          if (!c.in_body) {
            uint8_t tmp[sizeof(FrameHeader)];
            size_t need = sizeof(FrameHeader) - c.hdr.size();
            r = ::read(c.fd, tmp, need);
            if (r > 0) {
              c.hdr.insert(c.hdr.end(), tmp, tmp + r);
              cycle += static_cast<size_t>(r);
              if (c.hdr.size() == sizeof(FrameHeader)) {
                FrameHeader h;
                memcpy(&h, c.hdr.data(), sizeof(h));
                if (h.magic == kHbMagic && h.len == 0) {
                  // liveness beat: never enters the inbox
                  c.src_hint = h.src;
                  note_heard(h.src);
                  c.hdr.clear();
                  continue;
                }
                // Corrupt stream (bad magic, negative or absurd length):
                // drop the connection rather than buffering unboundedly.
                if (h.magic != kMagic || h.len < 0 ||
                    h.len > max_frame_bytes()) {
                  dead = true;
                  break;
                }
                c.src_hint = h.src;
                note_heard(h.src);
                c.cur.src = h.src;
                c.cur.len = static_cast<size_t>(h.len);
                c.cur.data.reset(c.cur.len ? new uint8_t[c.cur.len] : nullptr);
                c.filled = 0;
                c.in_body = true;
                c.hdr.clear();
                if (h.len == 0) {
                  push_frame(std::move(c.cur));
                  c.cur = Frame{};
                  c.in_body = false;
                }
              }
              continue;
            }
          } else {
            size_t want = c.cur.len - c.filled;
            if (want > kReadSlab) want = kReadSlab;
            r = ::read(c.fd, c.cur.data.get() + c.filled, want);
            if (r > 0) {
              c.filled += static_cast<size_t>(r);
              cycle += static_cast<size_t>(r);
              if (c.filled == c.cur.len) {
                push_frame(std::move(c.cur));
                c.cur = Frame{};
                c.in_body = false;
              }
              continue;
            }
          }
          if (r == 0) {
            dead = true;                         // orderly peer close
          } else if (errno == EINTR) {
            continue;
          } else if (errno != EAGAIN && errno != EWOULDBLOCK) {
            dead = true;
          }
          break;
        }
        if (dead) {
          // a conn that ever carried a frame names its rank: a closed
          // socket is peer death, not just a stream error
          if (c.src_hint >= 0) mark_dead(c.src_hint);
          ::close(c.fd);
          c.fd = -1;
          continue;
        }
      }
      conns_.erase(std::remove_if(conns_.begin(), conns_.end(),
                                  [](const Conn& c) { return c.fd < 0; }),
                   conns_.end());
    }
  }

  int rank_;
  std::atomic<int> size_;
  int listen_fd_ = -1;
  int port_ = -1;
  int wake_pipe_[2] = {-1, -1};
  std::mutex peers_mtx_;
  std::vector<std::string> peer_addrs_;
  // deques: growth must not move live slots (grow() appends while sends to
  // existing peers hold references into them)
  std::deque<int> peer_fds_;
  std::deque<std::mutex> peer_locks_;
  std::mutex q_mtx_;
  std::condition_variable q_cv_;
  std::deque<Frame> inbox_;
  // lock-free mirrors for hot_spin(): queue depth + last-arrival stamp
  std::atomic<int> inbox_n_{0};
  std::atomic<int64_t> last_push_us_{0};
  // direct-drain lease: exactly one thread (the progress thread or a
  // direct-receiving rank thread) runs pump_io at a time; the waiter count
  // + recency stamp park the progress thread while receivers drive the
  // engine inline (see recv(direct=true))
  std::mutex io_mtx_;
  std::atomic<int> direct_waiters_{0};
  std::atomic<int64_t> last_direct_us_{0};
  std::atomic<int> yield_req_{0};
  // failure detection (hb_enable): per-world-rank liveness, off by default
  std::atomic<bool> hb_enabled_{false};
  std::atomic<int> hb_interval_ms_{0};
  std::atomic<int64_t> hb_last_sent_us_{0};
  std::mutex hb_mtx_;
  std::vector<int64_t> last_heard_us_;
  std::vector<uint8_t> peer_dead_;
  std::thread progress_;
  std::atomic<bool> stopped_{false};
  std::vector<Conn> conns_;
};

// ---------------------------------------------------------------------------
// FdEngine: the serve tier's C10k front door (tmfd_* below).
//
// A second, independent consumer of this file's poll machinery: where
// Transport multiplexes a FIXED set of rank peers, FdEngine watches an
// arbitrary churning population of session sockets (attach/detach at
// thousands per second) with edge-triggered epoll. It owns no buffers and
// parses no frames — readiness events surface to Python, where the serve
// front door (tpu_mpi/serve/frontdoor.py) runs the incremental frame parser
// and worker pool. A self-pipe gives the Python loop a cross-thread wakeup
// (close requests, deferred writability) without a timeout tick.
struct FdEngine {
  int epfd = -1;
  int wake_rd = -1;
  int wake_wr = -1;
};

static bool set_nonblock(int fd) {
  int fl = ::fcntl(fd, F_GETFL, 0);
  return fl >= 0 && ::fcntl(fd, F_SETFL, fl | O_NONBLOCK) == 0;
}

}  // namespace

extern "C" {

void* tmfd_create(void) {
  auto* e = new FdEngine();
  e->epfd = ::epoll_create1(EPOLL_CLOEXEC);
  int p[2] = {-1, -1};
  if (e->epfd < 0 || ::pipe(p) != 0) {
    if (e->epfd >= 0) ::close(e->epfd);
    delete e;
    return nullptr;
  }
  e->wake_rd = p[0];
  e->wake_wr = p[1];
  set_nonblock(e->wake_rd);
  set_nonblock(e->wake_wr);
  // the wake pipe is level-triggered: a wakeup posted between epoll_wait
  // calls must not be lost
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = e->wake_rd;
  if (::epoll_ctl(e->epfd, EPOLL_CTL_ADD, e->wake_rd, &ev) != 0) {
    ::close(e->epfd);
    ::close(e->wake_rd);
    ::close(e->wake_wr);
    delete e;
    return nullptr;
  }
  return e;
}

// Register a session socket: edge-triggered read/ hangup interest, and the
// fd is flipped nonblocking here so callers cannot forget (ET + a blocking
// read is a deadlock). events bit 1 adds EPOLLOUT interest (deferred-write
// resume).
int tmfd_add(void* h, int fd, int want_write) {
  auto* e = static_cast<FdEngine*>(h);
  if (!set_nonblock(fd)) return -1;
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP | EPOLLET;
  if (want_write) ev.events |= EPOLLOUT;
  ev.data.fd = fd;
  return ::epoll_ctl(e->epfd, EPOLL_CTL_ADD, fd, &ev) == 0 ? 0 : -1;
}

int tmfd_mod(void* h, int fd, int want_write) {
  auto* e = static_cast<FdEngine*>(h);
  epoll_event ev{};
  ev.events = EPOLLIN | EPOLLRDHUP | EPOLLET;
  if (want_write) ev.events |= EPOLLOUT;
  ev.data.fd = fd;
  return ::epoll_ctl(e->epfd, EPOLL_CTL_MOD, fd, &ev) == 0 ? 0 : -1;
}

int tmfd_del(void* h, int fd) {
  auto* e = static_cast<FdEngine*>(h);
  return ::epoll_ctl(e->epfd, EPOLL_CTL_DEL, fd, nullptr) == 0 ? 0 : -1;
}

// Block up to timeout_ms for readiness. Fills fds_out/events_out (capacity
// max_events) and returns the count; the wake pipe is drained and reported
// as fd -1 with events 0 so the caller can count wakeups without watching a
// reserved fd. events_out bits: 1 = readable/hangup, 2 = writable.
int tmfd_wait(void* h, int* fds_out, int* events_out, int max_events,
              int timeout_ms) {
  auto* e = static_cast<FdEngine*>(h);
  if (max_events <= 0) return 0;
  std::vector<epoll_event> evs(static_cast<size_t>(max_events));
  int n = ::epoll_wait(e->epfd, evs.data(), max_events, timeout_ms);
  if (n < 0) return errno == EINTR ? 0 : -1;
  int out = 0;
  for (int i = 0; i < n; i++) {
    if (evs[i].data.fd == e->wake_rd) {
      char sink[256];
      while (::read(e->wake_rd, sink, sizeof sink) > 0) {
      }
      fds_out[out] = -1;
      events_out[out++] = 0;
      continue;
    }
    int bits = 0;
    if (evs[i].events & (EPOLLIN | EPOLLRDHUP | EPOLLHUP | EPOLLERR)) bits |= 1;
    if (evs[i].events & EPOLLOUT) bits |= 2;
    fds_out[out] = evs[i].data.fd;
    events_out[out++] = bits;
  }
  return out;
}

void tmfd_wake(void* h) {
  auto* e = static_cast<FdEngine*>(h);
  char b = 1;
  ssize_t rc = ::write(e->wake_wr, &b, 1);
  (void)rc;  // a full pipe already guarantees a pending wakeup
}

void tmfd_destroy(void* h) {
  auto* e = static_cast<FdEngine*>(h);
  if (e->epfd >= 0) ::close(e->epfd);
  if (e->wake_rd >= 0) ::close(e->wake_rd);
  if (e->wake_wr >= 0) ::close(e->wake_wr);
  delete e;
}

// Kernel byte pump for the router's splice mode: move up to budget bytes
// from src to dst through the caller's pipe (pipe_rd/pipe_wr) without the
// bytes ever surfacing to userspace. src must be nonblocking. Returns
// bytes moved (> 0), 0 on clean EOF at src, -1 when src has nothing to
// read right now (EAGAIN), -2 on a hard error on either side. Bytes pulled
// into the pipe are always fully drained to dst before returning (waiting
// for dst writability if needed) so the pipe never retains data between
// calls.
long long tmfd_splice(int src, int dst, int pipe_rd, int pipe_wr,
                      long long budget) {
  long long moved = 0;
  while (moved < budget) {
    ssize_t in = ::splice(src, nullptr, pipe_wr, nullptr,
                          static_cast<size_t>(budget - moved),
                          SPLICE_F_MOVE | SPLICE_F_NONBLOCK);
    if (in < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK)
        return moved > 0 ? moved : -1;
      return moved > 0 ? moved : -2;
    }
    if (in == 0) return moved;  // EOF (0 if nothing moved this call)
    long long pending = in;
    while (pending > 0) {
      ssize_t out = ::splice(pipe_rd, nullptr, dst, nullptr,
                             static_cast<size_t>(pending),
                             SPLICE_F_MOVE | SPLICE_F_NONBLOCK);
      if (out < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
          pollfd p{dst, POLLOUT, 0};
          if (::poll(&p, 1, 5000) <= 0) return -2;
          continue;
        }
        return -2;
      }
      pending -= out;
      moved += out;
    }
  }
  return moved;
}

void* tm_create(int rank, int size) {
  auto* t = new Transport(rank, size);
  if (!t->listen_any()) {
    delete t;
    return nullptr;
  }
  return t;
}

int tm_port(void* h) { return static_cast<Transport*>(h)->port(); }

int tm_set_peers(void* h, const char* csv) {
  return static_cast<Transport*>(h)->set_peers(csv) ? 0 : -1;
}

int tm_grow(void* h, int new_size, const char* csv) {
  return static_cast<Transport*>(h)->grow(new_size, csv) ? 0 : -1;
}

int tm_send(void* h, int dst, const void* buf, long long len) {
  return static_cast<Transport*>(h)->send(dst, buf, len) ? 0 : -1;
}

int tm_sendv(void* h, int dst, const void** bufs, const long long* lens,
             int nbufs) {
  return static_cast<Transport*>(h)->sendv(
             dst, bufs, reinterpret_cast<const int64_t*>(lens), nbufs)
             ? 0
             : -1;
}

int tm_recv(void* h, void* buf, long long cap, int* src_out,
            long long* len_out, int timeout_ms, int direct) {
  int64_t len64 = 0;
  int rc = static_cast<Transport*>(h)->recv(buf, cap, src_out, &len64,
                                            timeout_ms, direct != 0);
  *len_out = len64;
  return rc;
}

void tm_poke(void* h) { static_cast<Transport*>(h)->request_yield(); }

void tm_hb_enable(void* h, int interval_ms) {
  static_cast<Transport*>(h)->hb_enable(interval_ms);
}

long long tm_peer_age_ms(void* h, int peer) {
  return static_cast<Transport*>(h)->peer_age_ms(peer);
}

void tm_stop(void* h) { static_cast<Transport*>(h)->stop(); }

void tm_destroy(void* h) {
  auto* t = static_cast<Transport*>(h);
  t->stop();
  delete t;
}

}  // extern "C"
