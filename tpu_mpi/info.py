"""Info: key-value hint objects.

Reference: /root/reference/src/info.jl — ``Info <: AbstractDict{Symbol,String}``
(:28), create/free (:32-48), set with ASCII+length validation (:50-58),
``infoval`` conversion of Bool/Int/lists (:67-71), get via the valuelen
two-step (:82-108), delete/length/iterate (:110-156).

TPU mapping (SURVEY.md §2.2): a plain dict of string hints passed to ops and
plumbed into compile options / donate hints. The C-side valuelen dance
disappears; validation (ASCII keys, bounded lengths) is kept so programs port
without surprises. INFO_NULL is the absent-hints sentinel.
"""

from __future__ import annotations

from collections.abc import MutableMapping
from typing import Any, Iterator, Optional

from . import error as _ec
from .error import MPIError

MAX_INFO_KEY = 255
MAX_INFO_VAL = 1024


def infoval(x: Any) -> str:
    """Normalize a value to its string form (src/info.jl:67-71):
    bools → "true"/"false", numbers → decimal, sequences → comma-joined."""
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, (int, float)):
        return str(x)
    if isinstance(x, str):
        return x
    if isinstance(x, (list, tuple)):
        return ", ".join(infoval(v) for v in x)
    raise MPIError(f"cannot convert {type(x).__name__} to an info value")


class Info(MutableMapping):
    """A dictionary of string hints with MPI-style validation."""

    def __init__(self, *args, **kwargs):
        self._d: dict[str, str] = {}
        self._freed = False
        for k, v in dict(*args, **kwargs).items():
            self[k] = v

    def _check(self) -> None:
        if self._freed:
            raise MPIError("operation on a freed Info", code=_ec.ERR_INFO)

    def __setitem__(self, key: Any, value: Any) -> None:
        self._check()
        key = str(key)
        if not key.isascii():
            raise MPIError("info keys must be ASCII", code=_ec.ERR_INFO_KEY)
        if len(key) > MAX_INFO_KEY:
            raise MPIError(f"info key longer than {MAX_INFO_KEY}",
                           code=_ec.ERR_INFO_KEY)
        val = infoval(value)
        if len(val) > MAX_INFO_VAL:
            raise MPIError(f"info value longer than {MAX_INFO_VAL}",
                           code=_ec.ERR_INFO_VALUE)
        self._d[key] = val

    def __getitem__(self, key: Any) -> str:
        self._check()
        return self._d[str(key)]

    def __delitem__(self, key: Any) -> None:
        self._check()
        del self._d[str(key)]

    def __iter__(self) -> Iterator[str]:
        self._check()
        return iter(self._d)

    def __len__(self) -> int:
        self._check()
        return len(self._d)

    def free(self) -> None:
        self._d.clear()
        self._freed = True

    def __repr__(self) -> str:
        return f"Info({self._d!r})"


INFO_NULL: Optional[Info] = None
