"""Datatypes: dtype mapping plus derived-layout descriptors.

Reference: /root/reference/src/datatypes.jl — Datatype handle (:16), table of 23
predefined MPI↔Julia types (:29-60), the MPI.Types submodule: extent (:77-86),
create_contiguous (:99-107), create_vector (:142-152), create_subarray
(:171-190), create_struct (:203-221), create_resized (:241-251), commit!
(:262-266), and the automatic recursive ``Datatype(T)`` for any isbits struct
(:269-316) that walks field offsets, coalesces adjacent equal fields and
decomposes odd sizes into UInt blocks.

TPU mapping (SURVEY.md §2.2): a datatype = (numpy dtype, layout) descriptor.
XLA owns physical layout, so vector/subarray become strided/sliced element maps
used to pack to and unpack from contiguous wire buffers; struct types map to
numpy structured dtypes; the isbits auto-derivation becomes recursive structured
-dtype construction from dataclasses / NamedTuples / nested numpy records.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np

from . import error as _ec
from .error import MPIError


class Datatype:
    """A wire-format descriptor.

    ``blocks`` is a flat list of ``(byte_offset, numpy_dtype, count)`` runs
    within one extent — the same normal form the reference builds for isbits
    structs (src/datatypes.jl:269-316). ``extent`` is the stride between
    consecutive elements in a buffer; ``size`` is the number of payload bytes.
    """

    def __init__(self, np_dtype: Optional[np.dtype] = None, *,
                 blocks: Optional[list[tuple[int, np.dtype, int]]] = None,
                 extent: Optional[int] = None, lb: int = 0,
                 name: str = "datatype", committed: bool = True):
        if np_dtype is not None:
            np_dtype = np.dtype(np_dtype)
            if blocks is None:
                blocks = _blocks_from_np_dtype(np_dtype)
            if extent is None:
                extent = np_dtype.itemsize
        if blocks is None:
            raise MPIError("datatype needs an np_dtype or explicit blocks",
                           code=_ec.ERR_TYPE)
        self.np_dtype = np_dtype            # None for non-record derived layouts
        self.blocks = blocks
        self.lb = lb
        self.extent_bytes = extent if extent is not None else _blocks_span(blocks)
        self.size_bytes = sum(dt.itemsize * c for (_, dt, c) in blocks)
        self.name = name
        self.committed = committed
        self._freed = False

    # -- queries -------------------------------------------------------------
    def extent(self) -> tuple[int, int]:
        """(lower bound, extent) in bytes (src/datatypes.jl:77-86)."""
        return (self.lb, self.extent_bytes)

    @property
    def is_primitive(self) -> bool:
        return (self.np_dtype is not None and self.np_dtype.fields is None
                and len(self.blocks) == 1 and self.blocks[0] == (0, self.np_dtype, 1))

    # -- pack/unpack: derived layout <-> contiguous wire bytes ---------------
    def pack(self, raw: memoryview, count: int, base_offset: int = 0) -> bytes:
        """Gather ``count`` elements of this layout from raw bytes."""
        out = bytearray(self.size_bytes * count)
        pos = 0
        for i in range(count):
            elem = base_offset + self.lb + i * self.extent_bytes
            for (off, dt, c) in self.blocks:
                n = dt.itemsize * c
                out[pos:pos + n] = raw[elem + off: elem + off + n]
                pos += n
        return bytes(out)

    def unpack(self, wire: memoryview, raw: memoryview, count: int,
               base_offset: int = 0) -> None:
        """Scatter ``count`` packed elements back into raw bytes."""
        pos = 0
        for i in range(count):
            elem = base_offset + self.lb + i * self.extent_bytes
            for (off, dt, c) in self.blocks:
                n = dt.itemsize * c
                raw[elem + off: elem + off + n] = wire[pos: pos + n]
                pos += n

    def free(self) -> None:
        self._freed = True

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, Datatype) and self.blocks == other.blocks
                and self.extent_bytes == other.extent_bytes and self.lb == other.lb)

    def __hash__(self) -> int:
        return hash((tuple(self.blocks), self.extent_bytes, self.lb))

    def __repr__(self) -> str:
        return f"<Datatype {self.name} size={self.size_bytes} extent={self.extent_bytes}>"


def _blocks_from_np_dtype(dt: np.dtype, base: int = 0) -> list[tuple[int, np.dtype, int]]:
    """Flatten a (possibly structured / sub-arrayed) numpy dtype into runs —
    the analog of the recursive field walk in src/datatypes.jl:276-316."""
    if dt.fields is None:
        if dt.subdtype is not None:
            sub, shape = dt.subdtype
            n = int(np.prod(shape))
            inner = _blocks_from_np_dtype(sub)
            if len(inner) == 1 and inner[0][0] == 0:
                off, idt, c = inner[0]
                return [(base, idt, c * n)]
            out = []
            for i in range(n):
                for (off, idt, c) in inner:
                    out.append((base + i * sub.itemsize + off, idt, c))
            return out
        return [(base, dt, 1)]
    out: list[tuple[int, np.dtype, int]] = []
    for fname in dt.names:
        fdt, foff = dt.fields[fname][:2]
        out.extend(_blocks_from_np_dtype(fdt, base + foff))
    # Coalesce adjacent equal-dtype runs (src/datatypes.jl:283-292).
    return _coalesce(out)


# -- predefined datatypes (src/datatypes.jl:29-60) ----------------------------
def _predef(np_type: Any, name: str) -> Datatype:
    return Datatype(np.dtype(np_type), name=name)


INT8 = _predef(np.int8, "INT8")
INT16 = _predef(np.int16, "INT16")
INT32 = _predef(np.int32, "INT32")
INT64 = _predef(np.int64, "INT64")
UINT8 = _predef(np.uint8, "UINT8")
UINT16 = _predef(np.uint16, "UINT16")
UINT32 = _predef(np.uint32, "UINT32")
UINT64 = _predef(np.uint64, "UINT64")
FLOAT16 = _predef(np.float16, "FLOAT16")
FLOAT32 = _predef(np.float32, "FLOAT32")
FLOAT64 = _predef(np.float64, "FLOAT64")
COMPLEX64 = _predef(np.complex64, "COMPLEX64")
COMPLEX128 = _predef(np.complex128, "COMPLEX128")
BOOL = _predef(np.bool_, "BOOL")
BYTE = _predef(np.uint8, "BYTE")
CHAR = _predef(np.uint32, "CHAR")       # Julia Char is UInt32 (src/datatypes.jl:44)
try:
    BFLOAT16 = Datatype(np.dtype("bfloat16"), name="BFLOAT16")
except TypeError:
    try:
        import ml_dtypes
        BFLOAT16 = Datatype(np.dtype(ml_dtypes.bfloat16), name="BFLOAT16")
    except Exception:   # pragma: no cover
        BFLOAT16 = None

_PY_MAP = {int: INT64, float: FLOAT64, complex: COMPLEX128, bool: BOOL}


_DTYPE_CACHE: dict = {}      # plain np.dtype -> Datatype (per-message hot path)


def to_datatype(T: Any) -> Datatype:
    """``Datatype(T)`` for a Python/numpy/dataclass type (src/datatypes.jl:269-316)."""
    if isinstance(T, Datatype):
        return T
    if isinstance(T, np.dtype):
        # every typed send resolves its array's dtype here — memoize the
        # handful of plain dtypes (structured dtypes skip the cache: their
        # identity can embed mutable field metadata)
        if T.names is None:
            dt = _DTYPE_CACHE.get(T)
            if dt is None:
                dt = Datatype(T, name=str(T))
                _DTYPE_CACHE[T] = dt
            return dt
    if T in _PY_MAP:
        return _PY_MAP[T]
    if dataclasses.is_dataclass(T) or (isinstance(T, type) and issubclass(T, tuple)
                                       and hasattr(T, "_fields")):
        return Datatype(struct_np_dtype(T), name=getattr(T, "__name__", "struct"))
    try:
        return Datatype(np.dtype(T), name=str(np.dtype(T)))
    except TypeError:
        raise MPIError(f"no wire datatype for {T!r}", code=_ec.ERR_TYPE) from None


def struct_np_dtype(T: Any) -> np.dtype:
    """Recursive structured-dtype construction for dataclasses / NamedTuples —
    the auto isbits derivation (src/datatypes.jl:269-316) done the numpy way."""
    if dataclasses.is_dataclass(T):
        items = [(f.name, f.type) for f in dataclasses.fields(T)]
    elif isinstance(T, type) and issubclass(T, tuple) and hasattr(T, "_fields"):
        hints = T.__annotations__
        items = [(n, hints[n]) for n in T._fields]
    else:
        raise MPIError(f"not a struct-like type: {T!r}", code=_ec.ERR_TYPE)
    fields = []
    for name, ftype in items:
        if dataclasses.is_dataclass(ftype) or (isinstance(ftype, type)
                                               and issubclass(ftype, tuple)
                                               and hasattr(ftype, "_fields")):
            fields.append((name, struct_np_dtype(ftype)))
        elif ftype in _PY_MAP:
            fields.append((name, _PY_MAP[ftype].np_dtype))
        else:
            fields.append((name, np.dtype(ftype)))
    return np.dtype(fields, align=True)   # align=True keeps C padding like isbits


class Types:
    """Derived-datatype constructors (the MPI.Types submodule)."""

    @staticmethod
    def extent(dt: Datatype) -> tuple[int, int]:
        return dt.extent()

    @staticmethod
    def create_contiguous(count: int, base: Datatype) -> Datatype:
        """count consecutive elements (src/datatypes.jl:99-107)."""
        blocks: list[tuple[int, np.dtype, int]] = []
        for i in range(count):
            for (off, dt, c) in base.blocks:
                blocks.append((i * base.extent_bytes + base.lb + off, dt, c))
        return Datatype(blocks=_coalesce(blocks), extent=count * base.extent_bytes,
                        name=f"contiguous({count},{base.name})", committed=False)

    @staticmethod
    def create_vector(count: int, blocklength: int, stride: int,
                      base: Datatype) -> Datatype:
        """count blocks of blocklength elements, stride elements apart
        (src/datatypes.jl:142-152)."""
        blocks: list[tuple[int, np.dtype, int]] = []
        for i in range(count):
            start = i * stride * base.extent_bytes
            for j in range(blocklength):
                for (off, dt, c) in base.blocks:
                    blocks.append((start + j * base.extent_bytes + base.lb + off, dt, c))
        extent = ((count - 1) * stride + blocklength) * base.extent_bytes if count else 0
        return Datatype(blocks=_coalesce(blocks), extent=extent,
                        name=f"vector({count},{blocklength},{stride})", committed=False)

    @staticmethod
    def create_subarray(sizes: Sequence[int], subsizes: Sequence[int],
                        offsets: Sequence[int], base: Datatype,
                        order: str = "C") -> Datatype:
        """N-d subarray of a larger array (src/datatypes.jl:171-190);
        order 'C' (row-major) or 'F' (column-major, the Julia default)."""
        sizes = tuple(int(s) for s in sizes)
        subsizes = tuple(int(s) for s in subsizes)
        offsets = tuple(int(s) for s in offsets)
        idx = np.meshgrid(*[np.arange(o, o + s) for o, s in zip(offsets, subsizes)],
                          indexing="ij")
        flat = np.ravel_multi_index([i.reshape(-1) for i in idx], sizes, order=order)
        flat = np.sort(flat)
        blocks: list[tuple[int, np.dtype, int]] = []
        for k in flat.tolist():
            start = k * base.extent_bytes
            for (off, dt, c) in base.blocks:
                blocks.append((start + base.lb + off, dt, c))
        extent = int(np.prod(sizes)) * base.extent_bytes
        return Datatype(blocks=_coalesce(blocks), extent=extent,
                        name=f"subarray({subsizes}of{sizes})", committed=False)

    @staticmethod
    def create_struct(blocklengths: Sequence[int], displacements: Sequence[int],
                      types: Sequence[Datatype]) -> Datatype:
        """General struct layout (src/datatypes.jl:203-221)."""
        blocks: list[tuple[int, np.dtype, int]] = []
        upper = 0
        for bl, disp, t in zip(blocklengths, displacements, types):
            for i in range(bl):
                for (off, dt, c) in t.blocks:
                    blocks.append((disp + i * t.extent_bytes + t.lb + off, dt, c))
            upper = max(upper, disp + bl * t.extent_bytes)
        return Datatype(blocks=_coalesce(blocks), extent=upper,
                        name="struct", committed=False)

    @staticmethod
    def create_resized(base: Datatype, lb: int, extent: int) -> Datatype:
        """Override lb/extent (src/datatypes.jl:241-251)."""
        return Datatype(blocks=list(base.blocks), extent=extent, lb=lb,
                        name=f"resized({base.name})", committed=False)

    @staticmethod
    def commit(dt: Datatype) -> Datatype:
        """Finalize a derived type for use (src/datatypes.jl:262-266)."""
        dt.committed = True
        return dt


def _coalesce(blocks: list[tuple[int, np.dtype, int]]) -> list[tuple[int, np.dtype, int]]:
    merged: list[tuple[int, np.dtype, int]] = []
    for blk in sorted(blocks, key=lambda b: b[0]):
        if merged:
            poff, pdt, pc = merged[-1]
            off, bdt, c = blk
            if pdt == bdt and poff + pdt.itemsize * pc == off:
                merged[-1] = (poff, pdt, pc + c)
                continue
        merged.append(blk)
    return merged


def Get_address(obj: Any) -> int:
    """Address of a buffer (src/datatypes.jl:321-325)."""
    arr = np.asarray(obj)
    return arr.__array_interface__["data"][0]
