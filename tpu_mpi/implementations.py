"""Backend/platform introspection.

Reference: /root/reference/src/implementations.jl — queries
MPI_Get_library_version (:15-27), regex-parses vendor+version into an MPIImpl
enum (:57-66,80-132), and exposes MPI_VERSION (:154-170). The TPU analog
(SURVEY.md §2.1): identify the accelerator platform (TPU generation / CPU sim),
the runtime library (jax/jaxlib/libtpu versions), and the interconnect
topology, so programs can adapt like MPI programs adapt to MPICH vs OpenMPI.
"""

from __future__ import annotations

import enum
import functools
import re
from typing import Optional


class Backend(enum.Enum):
    """The transport 'implementation' (analog of MPIImpl, implementations.jl:57-66)."""
    UNKNOWN = 0
    CPU_SIM = 1        # fake XLA CPU devices (test substrate, SURVEY.md §3.5)
    TPU = 2            # real TPU chips over ICI
    GPU = 3            # jax on GPU (works, but not the design target)


# Pattern table: device-kind string -> TPU generation (the analog of the
# vendor version-string regexes in implementations.jl:80-132).
_TPU_KINDS = [
    (re.compile(r"v6|trillium", re.I), "v6"),
    (re.compile(r"v5p", re.I), "v5p"),
    (re.compile(r"v5e|v5 ?lite", re.I), "v5e"),
    (re.compile(r"v4", re.I), "v4"),
    (re.compile(r"v3", re.I), "v3"),
    (re.compile(r"v2", re.I), "v2"),
]


@functools.lru_cache(maxsize=1)
def _devices():
    import jax
    return jax.devices()


def get_backend() -> Backend:
    """Which transport backs the job (implementations.jl MPI_LIBRARY analog)."""
    try:
        platform = _devices()[0].platform
    except Exception:
        return Backend.UNKNOWN
    if platform == "tpu":
        return Backend.TPU
    if platform == "cpu":
        return Backend.CPU_SIM
    if platform in ("gpu", "cuda", "rocm"):
        return Backend.GPU
    return Backend.UNKNOWN


def tpu_generation() -> Optional[str]:
    """'v5e' / 'v5p' / … or None off-TPU (the per-generation capability key
    SURVEY.md §2.4 asks for)."""
    if get_backend() is not Backend.TPU:
        return None
    kind = _devices()[0].device_kind
    for pat, gen in _TPU_KINDS:
        if pat.search(kind):
            return gen
    return None


def Get_library_version() -> str:
    """Version string of the runtime stack (implementations.jl:15-27)."""
    import jax
    import jaxlib
    parts = [f"jax {jax.__version__}", f"jaxlib {jaxlib.__version__}"]
    try:
        d = _devices()[0]
        parts.append(f"platform {d.platform} ({d.device_kind})")
    except Exception:
        pass
    return ", ".join(parts)


def Get_version() -> tuple[int, int]:
    """API version of this framework (implementations.jl:154-170 reports the
    MPI standard version; we report the capability surface we mirror)."""
    return (3, 1)


def device_count() -> int:
    return len(_devices())


def ici_topology() -> Optional[tuple[int, ...]]:
    """Physical torus coordinates bounds of the local slice, when the runtime
    exposes them (None on CPU sim). Used for torus-aware Dims_create."""
    try:
        devs = _devices()
        coords = [getattr(d, "coords", None) for d in devs]
        if any(c is None for c in coords):
            return None
        dims = tuple(max(c[i] for c in coords) + 1 for i in range(len(coords[0])))
        return dims
    except Exception:
        return None


# ---------------------------------------------------------------------------
# Per-generation capability tables — the analog of the pre-baked ABI constant
# tables deps/consts_mpich.jl / consts_openmpi.jl / consts_microsoftmpi.jl
# (SURVEY.md §2.4): public chip-level numbers programs and benchmarks consult
# to contextualize measurements (aggregate one-way ICI GB/s per chip, HBM
# GB/s and capacity per chip, TensorCores per chip, peak bf16 TFLOP/s).
# ---------------------------------------------------------------------------

CAPABILITIES: dict[str, dict[str, float]] = {
    "v2":  {"ici_gbps": 62.5,  "hbm_gbps": 300.0,  "hbm_gib": 16.0,
            "cores": 2, "bf16_tflops": 46.0},
    "v3":  {"ici_gbps": 112.5, "hbm_gbps": 450.0,  "hbm_gib": 32.0,
            "cores": 2, "bf16_tflops": 123.0},
    "v4":  {"ici_gbps": 270.0, "hbm_gbps": 1228.0, "hbm_gib": 32.0,
            "cores": 2, "bf16_tflops": 275.0},
    "v5e": {"ici_gbps": 180.0, "hbm_gbps": 819.0,  "hbm_gib": 16.0,
            "cores": 1, "bf16_tflops": 197.0},
    "v5p": {"ici_gbps": 540.0, "hbm_gbps": 2765.0, "hbm_gib": 95.0,
            "cores": 2, "bf16_tflops": 459.0},
    "v6":  {"ici_gbps": 448.0, "hbm_gbps": 1638.0, "hbm_gib": 32.0,
            "cores": 1, "bf16_tflops": 918.0},
}


def platform_probe() -> dict:
    """One-shot platform report — the runtime analog of the reference's
    build-time ``gen_consts`` probe (/root/reference/deps/gen_consts.jl:
    compiled and executed under mpiexec to discover the ABI's constants).
    Here the 'ABI' is the accelerator platform: backend, TPU generation,
    device inventory with physical coords, torus bounds, process metadata,
    and the generation's capability constants. ``tpurun --probe`` prints it
    as JSON."""
    report: dict = {
        "backend": get_backend().name,
        "library_version": Get_library_version(),
        "api_version": list(Get_version()),
        "generation": tpu_generation(),
        "device_count": device_count(),
        "ici_topology": (list(ici_topology()) if ici_topology() else None),
        "capabilities": capabilities(),
    }
    try:
        import jax
        report["devices"] = [{
            "id": d.id,
            "kind": getattr(d, "device_kind", "?"),
            "process": getattr(d, "process_index", 0),
            "coords": (list(d.coords)
                       if getattr(d, "coords", None) is not None else None),
            "core_on_chip": getattr(d, "core_on_chip", None),
        } for d in _devices()]
        report["process_count"] = jax.process_count()
        report["process_index"] = jax.process_index()
    except Exception:
        pass
    return report


def capabilities(generation: Optional[str] = None) -> dict[str, float]:
    """Capability row for a generation (default: the local chip; a modest
    v5e row when the generation is unknown so ratios stay computable)."""
    gen = generation or tpu_generation()
    return dict(CAPABILITIES.get(gen or "", CAPABILITIES["v5e"]))


MPI_LIBRARY = "tpu_mpi"
