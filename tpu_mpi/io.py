"""Parallel file I/O: shared-file explicit-offset reads/writes with views.

Reference: /root/reference/src/io.jl — FileHandle (:1-3), File.open with
Julia-style kwargs→amode flags (:12-62), close (:64-72), set_view!
(disp+etype+filetype+datarep, :87-98), sync (:111-115), read_at! (:131-140),
read_at_all! collective (:155-165), write_at (:179-188), write_at_all
collective (:203-212).

TPU mapping (SURVEY.md §2.3): POSIX pread/pwrite at rank-computed offsets into
one shared file, with rendezvous barriers bracketing the ``_all`` collective
variants; datatype file views become offset arithmetic — an element index maps
through the filetype's block pattern tiled from ``disp``. This is also the
checkpoint substrate (SURVEY.md §5: "checkpoint/resume parity = the File
layer"); a tensorstore/Zarr backend can slot behind the same API later.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from .buffers import Buffer, extract_array, to_wire, write_flat
from .comm import Comm
from .datatypes import BYTE, Datatype, to_datatype
from . import error as _ec
from .error import MPIError
from .pointtopoint import Status


class FileHandle:
    """An open shared file plus this rank's view (src/io.jl:1-3).

    Each rank holds its own OS file descriptor on the shared path; the view
    (disp, etype, filetype) is per-rank state exactly as in MPI.
    """

    def __init__(self, comm: Comm, path: str, fd: int, deleteonclose: bool):
        self.comm = comm
        self.path = path
        self.fd: Optional[int] = fd
        self.deleteonclose = deleteonclose
        # Default view: displacement 0, etype = filetype = BYTE (byte offsets).
        self.disp = 0
        self.etype: Datatype = BYTE
        self.filetype: Datatype = BYTE
        self.datarep = "native"

    def _check(self) -> None:
        if self.fd is None:
            raise MPIError("file has been closed", code=_ec.ERR_FILE)

    def close(self) -> None:
        if self.fd is not None:
            os.close(self.fd)
            self.fd = None
            if self.deleteonclose and self.comm.rank() == 0:
                try:
                    os.unlink(self.path)
                except FileNotFoundError:
                    pass

    def __enter__(self) -> "FileHandle":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __repr__(self) -> str:
        state = "closed" if self.fd is None else "open"
        return f"<FileHandle {self.path!r} ({state})>"


def open(comm: Comm, filename: str, *, read: Optional[bool] = None,
         write: Optional[bool] = None, create: Optional[bool] = None,
         append: Optional[bool] = None, sequential: bool = False,
         uniqueopen: bool = False, deleteonclose: bool = False,
         **infokws) -> FileHandle:
    """Collectively open ``filename`` (src/io.jl:40-62). Keywords mirror the
    reference's Base.open-style flags; extra kwargs are Info hints."""
    do_read = bool(read) if read is not None else not bool(write)
    do_write = bool(write) if write is not None else False
    do_create = bool(create) if create is not None else do_write
    do_append = bool(append) if append is not None else False

    flags = 0
    if do_read and do_write:
        flags |= os.O_RDWR
    elif do_write:
        flags |= os.O_WRONLY
    else:
        flags |= os.O_RDONLY
    if do_write and do_create:
        flags |= os.O_CREAT
    if do_append:
        flags |= os.O_APPEND

    # Collective: rank 0 creates first so O_CREAT races cannot produce
    # different inodes on network filesystems; then everyone opens.
    rank = comm.rank()
    if rank == 0:
        fd = os.open(filename, flags, 0o644)
        comm.channel().run(rank, None, lambda cs: [None] * len(cs),
                           f"File.open@{comm.cid}")
    else:
        comm.channel().run(rank, None, lambda cs: [None] * len(cs),
                           f"File.open@{comm.cid}")
        fd = os.open(filename, flags, 0o644)
    return FileHandle(comm, filename, fd, deleteonclose)


def close(file: FileHandle) -> None:
    """Close the handle (src/io.jl:64-72)."""
    file.close()


def set_view(file: FileHandle, disp: int, etype: Any, filetype: Any,
             datarep: str = "native", **infokws) -> FileHandle:
    """Set this rank's file view (src/io.jl:87-98): data starts at byte
    ``disp``; offsets in read/write calls count ``etype`` elements; the
    ``filetype`` pattern tiles the file from disp."""
    file._check()
    file.disp = int(disp)
    file.etype = to_datatype(etype)
    file.filetype = to_datatype(filetype) if filetype is not None else file.etype
    file.datarep = datarep
    return file


# Julia-parity alias (set_view! in the reference).
set_view_ = set_view


def sync(file: FileHandle) -> None:
    """Flush writes to storage, collectively (src/io.jl:111-115)."""
    file._check()
    os.fsync(file.fd)
    file.comm.channel().run(file.comm.rank(), None, lambda cs: [None] * len(cs),
                            f"File.sync@{file.comm.cid}")


def _view_byte_ranges(file: FileHandle, offset_etype: int, nbytes: int):
    """Map a span of ``nbytes`` payload bytes starting at element offset
    ``offset_etype`` (in etype units) through the view to (file_byte, length)
    runs. Contiguous filetype ⇒ one run; holes in the filetype tile the
    pattern across extents."""
    et = file.etype
    ft = file.filetype
    esz = et.extent_bytes
    # Payload byte ranges inside one filetype extent, in pattern order.
    runs = [(off, dt.itemsize * c) for (off, dt, c) in ft.blocks]
    bytes_per_tile = sum(n for _, n in runs)
    if bytes_per_tile == ft.extent_bytes and len(runs) <= 1:
        # Dense view: plain offset arithmetic.
        start = file.disp + offset_etype * esz
        return [(start, nbytes)]
    out = []
    want_start = offset_etype * esz          # payload byte position
    want_end = want_start + nbytes
    tile = want_start // bytes_per_tile
    payload_pos = tile * bytes_per_tile
    while payload_pos < want_end:
        for (off, length) in runs:
            seg_start = payload_pos
            seg_end = payload_pos + length
            lo = max(seg_start, want_start)
            hi = min(seg_end, want_end)
            if lo < hi:
                file_byte = file.disp + tile * ft.extent_bytes + off + (lo - seg_start)
                out.append((file_byte, hi - lo))
            payload_pos = seg_end
        tile += 1
    return out


def _read_into(file: FileHandle, offset: int, data: Any) -> Status:
    file._check()
    buf = data if isinstance(data, Buffer) else Buffer(data)
    count = buf.count
    arr = extract_array(buf.data)
    # Payload length matches what _write_from emits: the raw array bytes
    # (itemsize includes struct padding; Datatype.size_bytes does not).
    nbytes = count * arr.dtype.itemsize
    chunks = []
    for (pos, length) in _view_byte_ranges(file, int(offset), nbytes):
        chunk = os.pread(file.fd, length, pos)
        if len(chunk) < length:
            chunk = chunk + b"\x00" * (length - len(chunk))   # short read past EOF
        chunks.append(chunk)
    raw = b"".join(chunks)
    vals = np.frombuffer(raw[:nbytes], dtype=arr.dtype, count=count)
    write_flat(buf.data, vals, count)
    return Status(source=0, tag=0, count=count)


def _write_from(file: FileHandle, offset: int, data: Any) -> Status:
    file._check()
    buf = data if isinstance(data, Buffer) else Buffer(data)
    count = buf.count
    wire = np.asarray(to_wire(buf.data, count))
    raw = wire.tobytes()
    pos_in = 0
    for (pos, length) in _view_byte_ranges(file, int(offset), len(raw)):
        os.pwrite(file.fd, raw[pos_in:pos_in + length], pos)
        pos_in += length
    return Status(source=0, tag=0, count=count)


def read_at(file: FileHandle, offset: int, data: Any) -> Status:
    """Noncollective read at explicit offset (src/io.jl:131-140).
    ``offset`` is in etype units of the current view."""
    return _read_into(file, offset, data)


def read_at_all(file: FileHandle, offset: int, data: Any) -> Status:
    """Collective read_at (src/io.jl:155-165): all ranks must call; barriers
    bracket the read so it observes every write issued before the collective."""
    comm = file.comm
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"File.read_at_all:pre@{comm.cid}")
    st = _read_into(file, offset, data)
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"File.read_at_all:post@{comm.cid}")
    return st


def write_at(file: FileHandle, offset: int, data: Any) -> Status:
    """Noncollective write at explicit offset (src/io.jl:179-188)."""
    return _write_from(file, offset, data)


def write_at_all(file: FileHandle, offset: int, data: Any) -> Status:
    """Collective write_at (src/io.jl:203-212)."""
    comm = file.comm
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"File.write_at_all:pre@{comm.cid}")
    st = _write_from(file, offset, data)
    comm.channel().run(comm.rank(), None, lambda cs: [None] * len(cs),
                       f"File.write_at_all:post@{comm.cid}")
    return st


def get_size(file: FileHandle) -> int:
    """File size in bytes (MPI_File_get_size)."""
    file._check()
    return os.fstat(file.fd).st_size


def set_size(file: FileHandle, size: int) -> None:
    """Collectively truncate/extend (MPI_File_set_size)."""
    file._check()
    os.ftruncate(file.fd, int(size))
    file.comm.channel().run(file.comm.rank(), None, lambda cs: [None] * len(cs),
                            f"File.set_size@{file.comm.cid}")


def delete(filename: str) -> None:
    """Delete a file (MPI_File_delete)."""
    try:
        os.unlink(filename)
    except FileNotFoundError:
        pass
