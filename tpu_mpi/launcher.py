"""tpurun: the SPMD launcher (the mpiexecjl analog).

Reference: /root/reference/bin/mpiexecjl (sh, :29-64) resolves the right
mpiexec and forks N OS processes each running ``julia script.jl``; ranks are
bound by libmpi at MPI_Init. TPU-native launch model (SURVEY.md §3.5):

- single host: ONE controller process owning all devices runs the script on
  N rank threads (rank i ↔ device i) — ``tpurun -n 4 script.py``;
- CPU-sim: same, with ``--sim N`` forcing N fake XLA CPU devices — the
  "cluster on a laptop" mode the reference gets from ``--oversubscribe``;
- multi-host: one process per host over DCN (``tpu_mpi.backend``), each
  launched with TPU_MPI_{NPROCS,RANK,COORD} set by the cluster scheduler.

Each rank executes the script the way ``runpy`` runs ``__main__``, with its
own module namespace; a nonzero exit of any rank fails the whole run
(test/runtests.jl:37-39 semantics).
"""

from __future__ import annotations

import argparse
import os
import time
import runpy
import sys
from typing import Optional

from ._runtime import spmd_run
from .error import MPIError

# Distinct job exit codes for the fault-tolerant launch mode
# (TPU_MPI_HEARTBEAT_MS > 0; docs/fault-tolerance.md):
# EXIT_SHRUNK_OK  — a rank died by signal, but every survivor finished
#                   cleanly (revoked + shrunk + completed).
# EXIT_RANK_FAILED — a rank failed and the job did NOT recover (a survivor
#                   also exited nonzero, or the failure wasn't a signal).
# Elastic-resize outcomes (docs/fault-tolerance.md "Elastic recovery";
# used by the serve-tier chaos driver, benchmarks/elastic_chaos.py):
# EXIT_RESIZED_OK — ranks were lost AND the autoscaler restored full
#                   capacity (degraded → re-spawn → rebind) with zero
#                   dropped tenants.
# EXIT_DEGRADED   — ranks were lost and the pool is still serving degraded
#                   (capacity not yet restored when the run ended).
EXIT_SHRUNK_OK = 66
EXIT_RANK_FAILED = 65
EXIT_RESIZED_OK = 67
EXIT_DEGRADED = 68


def _force_sim_devices(n: int) -> None:
    """Force n fake XLA CPU devices; must run before JAX backend init."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    if os.environ.get("PALLAS_AXON_POOL_IPS") and "jax" in sys.modules:
        import jax
        import jax._src.xla_bridge as xb
        jax.config.update("jax_platforms", "cpu")
        xb._backend_factories.pop("axon", None)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def launch_script(path: str, nprocs: int, script_args: Optional[list[str]] = None,
                  timeout: Optional[float] = None) -> None:
    """Run a Python script as an SPMD program on nprocs rank threads."""
    argv = [path] + list(script_args or [])

    def rank_main() -> None:
        runpy.run_path(path, run_name="__main__")

    # sys.argv is process-global; set it once around the whole SPMD run
    # rather than per rank-thread (a per-thread restore races with ranks
    # still running).
    old_argv = sys.argv
    sys.argv = list(argv)
    try:
        spmd_run(rank_main, nprocs, timeout=timeout)
    finally:
        sys.argv = old_argv


class Rendezvous:
    """The address-map bootstrap, factored so the classic ``tpurun --procs``
    path and the serve broker's process pool (docs/serving.md) share one
    implementation: children report their transport ports to a coordinator
    and every child receives the full world address map.

    Two construction modes mirror the two launch shapes:

    - ``Rendezvous(world, ...)`` creates the coordinator (first host /
      broker) — a :class:`tpu_mpi.backend.Coordinator` under the hood;
    - ``Rendezvous.join(addr, world)`` wraps an existing coordinator's
      address (hosts 2..H of a multi-host job) — same ``child_env`` surface,
      no local server.

    ``child_env(rank)`` builds the complete child environment: the
    ``TPU_MPI_PROC_{RANK,SIZE,COORD}`` rendezvous triple, a PYTHONPATH
    that resolves this tpu_mpi wherever the script lives, the exported
    frame-size knob, and the CPU-sim substrate flags when requested.
    """

    def __init__(self, world: int, *, port: int = 0,
                 host: Optional[str] = None,
                 advertise: Optional[str] = None,
                 rank_base: int = 0,
                 base_addrs: Optional[list[str]] = None):
        from . import config
        from .backend import Coordinator
        cfg = config.load()
        self.world = world
        self.coordinator = Coordinator(
            world, host=host or cfg.coordinator_bind, port=port,
            advertise=advertise if advertise is not None
            else (cfg.coordinator_advertise or None),
            rank_base=rank_base, base_addrs=base_addrs)
        self.address = self.coordinator.address
        self._swept = False

    @classmethod
    def join(cls, address: str, world: int) -> "Rendezvous":
        """An already-running coordinator elsewhere; this instance only
        builds child environments pointing at it."""
        self = cls.__new__(cls)
        self.world = world
        self.coordinator = None
        self.address = address
        self._swept = False
        return self

    def child_env(self, rank: int, *, sim: Optional[int] = None,
                  extra: Optional[dict] = None) -> dict:
        from . import config
        cfg = config.load()
        env = dict(os.environ)
        # Children run `python script.py`, whose sys.path[0] is the script's
        # directory — make sure they can import this tpu_mpi no matter where
        # the script lives (the mpiexecjl --project flag analog).
        pkg_parent = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        old_pp = env.get("PYTHONPATH", "")
        env["PYTHONPATH"] = pkg_parent + (os.pathsep + old_pp if old_pp else "")
        env["TPU_MPI_PROC_RANK"] = str(rank)
        env["TPU_MPI_PROC_SIZE"] = str(self.world)
        env["TPU_MPI_PROC_COORD"] = self.address
        # The native transport reads knobs from the environment only;
        # export the merged config so TOML-persisted values reach children.
        env.setdefault("TPU_MPI_MAX_FRAME_BYTES", str(cfg.max_frame_bytes))
        if sim is not None:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + f" --xla_force_host_platform_device_count={sim}"
                ).strip()
            env.pop("PALLAS_AXON_POOL_IPS", None)
        if extra:
            env.update(extra)
        return env

    def wait_map(self, timeout: float) -> list[str]:
        """Block until every expected registrant arrived; the full world
        address table."""
        if self.coordinator is None:
            raise MPIError("wait_map on a joined Rendezvous (the map lives "
                           "at the remote coordinator)")
        return self.coordinator.wait_map(timeout)

    def close(self, sweep: bool = False) -> None:
        """Stop the coordinator; ``sweep=True`` additionally reclaims
        shm-lane segments orphaned by crashed children — only safe once
        every child is really gone (a rank still mid-spill would recreate
        segments after the sweep)."""
        if self.coordinator is not None:
            self.coordinator.close()
        if sweep and not self._swept:
            self._swept = True
            from .backend import sweep_segments
            sweep_segments(self.address.rsplit(":", 1)[-1])


def launch_processes(path: str, nprocs: int,
                     script_args: Optional[list[str]] = None,
                     timeout: Optional[float] = None,
                     sim: Optional[int] = None,
                     world_size: Optional[int] = None,
                     rank_base: int = 0,
                     coordinator: Optional[str] = None,
                     coord_port: int = 0) -> int:
    """Run a script as N OS processes over the native transport (the
    reference's actual launch model, bin/mpiexecjl:55-64: mpiexec forks N
    processes; ranks bind at Init). Returns the job exit code; any rank
    failing nonzero fails the job, mpiexec-style.

    Multi-host (SURVEY §3.5 "multi-host → per-host processes"): one tpurun
    invocation per host, each launching its local share of a bigger world —
    ``world_size`` = total ranks, ``rank_base`` = this host's first rank.
    The first host creates the rendezvous Coordinator (bind/advertise from
    config, fixed ``coord_port`` so peers can be pointed at it); the others
    pass ``coordinator="host:port"`` and join it.
    """
    import signal
    import subprocess

    world = world_size if world_size is not None else nprocs
    if not (0 <= rank_base and rank_base + nprocs <= world):
        raise MPIError(f"local ranks [{rank_base}, {rank_base + nprocs}) "
                       f"outside world of {world}")
    if coordinator is None:
        rdv = Rendezvous(world, port=coord_port)
        if world > nprocs:
            # remaining hosts need this address; print it where a wrapping
            # scheduler can scrape it
            print(f"tpurun: coordinator at {rdv.address} "
                  f"(waiting for {world - nprocs} remote ranks)",
                  file=sys.stderr, flush=True)
    else:
        rdv = Rendezvous.join(coordinator, world)
    coord_addr = rdv.address
    procs: list[subprocess.Popen] = []
    try:
        for rank in range(rank_base, rank_base + nprocs):
            env = rdv.child_env(rank, sim=sim)
            if sim is None:
                # Real-hardware procs tier: libtpu is process-exclusive, so
                # without a per-child chip assignment every rank process
                # would fight over the whole host's TPUs. Bind rank i of
                # this invocation to local chip i (the mpiexec local-rank ↔
                # accelerator convention). A caller-set TPU_VISIBLE_DEVICES
                # is treated as the allowed chip POOL: child i gets the
                # i-th entry (a verbatim pass-through would hand every
                # child the same multi-chip set — the very contention this
                # binding prevents).
                local_idx = rank - rank_base
                pool = env.get("TPU_VISIBLE_DEVICES")
                if pool is None:
                    env["TPU_VISIBLE_DEVICES"] = str(local_idx)
                else:
                    chips = [c.strip() for c in pool.split(",") if c.strip()]
                    if chips and local_idx >= len(chips):
                        # silently wrapping would double-bind a chip — the
                        # exact process-exclusive contention this prevents
                        raise SystemExit(
                            f"tpurun: TPU_VISIBLE_DEVICES lists "
                            f"{len(chips)} chip(s) but this invocation "
                            f"launches {nprocs} rank processes; provide at "
                            f"least one chip per local rank")
                    if chips:
                        env["TPU_VISIBLE_DEVICES"] = chips[local_idx]
            procs.append(subprocess.Popen(
                [sys.executable, path] + list(script_args or []), env=env))
        code = 0
        # Fault-tolerant mode: with the failure detector enabled in the
        # children (TPU_MPI_HEARTBEAT_MS > 0), a dead rank is the SCRIPT's
        # problem — survivors detect it, revoke, shrink and continue — so
        # the launcher must not fate-share-kill them. Without it, the
        # classic mpiexec behavior stands: one rank fails, all die.
        try:
            ft_mode = int(os.environ.get("TPU_MPI_HEARTBEAT_MS", "0") or 0) > 0
        except ValueError:
            ft_mode = False
        failures: list[tuple[int, int]] = []      # (rank, returncode)
        deadline = None if timeout is None else (time.monotonic() + timeout)
        pending = list(procs)
        while pending:
            for p in list(pending):
                rc = p.poll()
                if rc is None:
                    continue
                pending.remove(p)
                if rc != 0:
                    rank = rank_base + procs.index(p)
                    if rc < 0:
                        try:
                            desc = f"signal {signal.Signals(-rc).name}"
                        except ValueError:
                            desc = f"signal {-rc}"
                    else:
                        desc = f"exit code {rc}"
                    stamp = time.strftime("%Y-%m-%dT%H:%M:%S",
                                          time.localtime())
                    print(f"tpurun: rank {rank} died ({desc}) at {stamp}"
                          + ("" if failures else " [first failure]"),
                          file=sys.stderr, flush=True)
                    failures.append((rank, rc))
                    if ft_mode:
                        continue          # survivors shrink and carry on
                    if code == 0:
                        code = rc
                        # fate-sharing: one rank failed, kill the rest
                        for q in pending:
                            q.terminate()
            if pending:
                if deadline is not None and time.monotonic() > deadline:
                    for q in pending:
                        q.terminate()
                    code = code or 124
                    break
                try:
                    pending[0].wait(timeout=0.2)
                except subprocess.TimeoutExpired:
                    pass
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        if ft_mode and failures and code == 0:
            # Distinct exit codes for the two fault outcomes: survivors all
            # finished cleanly after a signal death (revoked + shrunk +
            # completed) vs. the job genuinely failing.
            only_signals = all(rc < 0 for _, rc in failures)
            survivors_ok = len(failures) < nprocs
            code = (EXIT_SHRUNK_OK if only_signals and survivors_ok
                    else EXIT_RANK_FAILED)
        return code
    finally:
        rdv.close()
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        # Sweep shm-lane segments orphaned by a crashed/killed rank — but
        # only once every child is really gone, or a rank still mid-spill
        # would recreate segments after the sweep (a clean run unlinks every
        # segment at receive time; see backend._shm_load).
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
        rdv.close(sweep=True)


def install_tpurun(command: str = "tpurun",
                   destdir: Optional[str] = None,
                   force: bool = False, verbose: bool = True) -> str:
    """Install a ``tpurun`` wrapper executable (the install_mpiexecjl analog,
    src/mpiexec_wrapper.jl:12-26): a small script that launches this
    interpreter's ``tpu_mpi.launcher`` with the caller's arguments. Returns
    the installed path."""
    if destdir is None:
        destdir = os.path.join(os.path.expanduser("~"), ".local", "bin")
    destdir = os.path.abspath(os.path.expanduser(destdir))
    exec_path = os.path.join(destdir, command)
    if os.path.exists(exec_path) and not force:
        raise MPIError(f"file {exec_path!r} already exists; "
                       f"use install_tpurun(force=True) to overwrite")
    os.makedirs(destdir, exist_ok=True)
    if verbose:
        print(f"Installing {command!r} to {destdir!r}...")
    script = ("#!/bin/sh\n"
              f"exec \"{sys.executable}\" -m tpu_mpi.launcher \"$@\"\n")
    with open(exec_path, "w") as f:
        f.write(script)
    os.chmod(exec_path, 0o755)
    if verbose:
        print("Done!")
    return exec_path


def main(argv: Optional[list[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv[:1] == ["--tune"]:
        # `tpurun --tune [...]` — the collective-algorithm autotuner
        # (tpu_mpi.tune): sweep the portfolio on this substrate and write
        # a tuning table; `--tune merge` folds pvar dumps + tables into
        # the shared fleet database, `--tune sentinel` replays committed
        # artifacts as a regression check, and `--tune --online <dumps>`
        # reports the online bandit's exploration. All following args
        # belong to the tuner.
        from . import tune
        return tune.main(argv[1:])
    if argv[:1] == ["--serve"]:
        # `tpurun --serve [...]` — the multi-tenant broker daemon
        # (tpu_mpi.serve, docs/serving.md): own a warm world and lease
        # slices of it to client sessions; `--serve --stats` queries a
        # running broker's per-tenant ledger. All following args belong
        # to the broker CLI.
        from .serve import broker
        return broker.main(argv[1:])
    if argv[:1] == ["--stats"]:
        # `tpurun --stats <dumps...>` / `tpurun --stats -- <launch args>` —
        # the pvar report CLI (tpu_mpi.stats): aggregate per-rank counter
        # dumps into latency/bandwidth tables, or wrap a whole launch with
        # dumping enabled. All following args belong to the reporter.
        from . import stats
        return stats.main(argv[1:])
    p = argparse.ArgumentParser(
        prog="tpurun",
        description="Run an SPMD tpu_mpi program on N ranks (mpiexec analog); "
                    "`tpurun --tune` runs the collective autotuner and "
                    "`tpurun --stats` the pvar performance reporter")
    from . import config
    cfg = config.load()
    p.add_argument("-n", "--np", type=int, default=cfg.nprocs or None,
                   help="number of ranks (default: number of local devices)")
    p.add_argument("--sim", type=int, default=None, metavar="N",
                   help="simulate N XLA CPU devices (test mode); backend="
                        "cpu-sim in the config applies this by default")
    p.add_argument("--procs", action="store_true",
                   help="one OS process per rank over the native transport "
                        "(multi-host deployment shape) instead of rank threads")
    p.add_argument("--world-size", type=int, default=None, metavar="N",
                   help="total ranks across every host (multi-host --procs); "
                        "default: -n (single-host world)")
    p.add_argument("--rank-base", type=int, default=0, metavar="K",
                   help="first world rank launched by this invocation "
                        "(multi-host --procs)")
    # no config default here: cfg.coordinator maps TPU_MPI_PROC_COORD, the
    # env the launcher sets FOR children — a nested tpurun inheriting it
    # would register with the parent job's coordinator
    p.add_argument("--coordinator", default=None,
                   metavar="HOST:PORT",
                   help="join an existing rendezvous coordinator instead of "
                        "creating one (hosts 2..H of a multi-host job)")
    p.add_argument("--coord-port", type=int, default=0, metavar="P",
                   help="fixed port for the coordinator this invocation "
                        "creates (so other hosts can be pointed at it)")
    p.add_argument("--timeout", type=float, default=None,
                   help="abort the job after SECONDS")
    p.add_argument("--probe", action="store_true",
                   help="print the platform probe (backend, generation, "
                        "topology, capabilities) as JSON and exit")
    p.add_argument("script", nargs="?",
                   help="Python script to run on every rank")
    p.add_argument("script_args", nargs=argparse.REMAINDER,
                   help="arguments passed to the script")
    args = p.parse_args(argv)

    if args.probe:
        import json
        # same cpu-sim defaulting as a real launch, so the probe reports
        # the platform a job would actually run on
        if args.sim is None and cfg.backend == "cpu-sim":
            args.sim = cfg.sim_devices
        if args.sim is not None:
            _force_sim_devices(args.sim)
        from .implementations import platform_probe
        print(json.dumps(platform_probe(), indent=2))
        return 0
    if args.script is None:
        p.error("script is required (or use --probe)")

    if args.sim is None and config.load().backend == "cpu-sim":
        args.sim = config.load().sim_devices
    if args.sim is not None:
        _force_sim_devices(args.sim)
        if args.np is None:
            args.np = args.sim
    if args.np is None:
        try:
            import jax
            args.np = len(jax.devices())
        except Exception:
            args.np = 1
    try:
        if args.procs:
            return launch_processes(args.script, args.np, args.script_args,
                                    timeout=args.timeout, sim=args.sim,
                                    world_size=args.world_size,
                                    rank_base=args.rank_base,
                                    coordinator=args.coordinator,
                                    coord_port=args.coord_port)
        if args.world_size is not None or args.rank_base or args.coordinator:
            raise MPIError("--world-size/--rank-base/--coordinator require --procs")
        launch_script(args.script, args.np, args.script_args, timeout=args.timeout)
    except SystemExit as e:
        if e.code is None:
            return 0
        if isinstance(e.code, int):
            return e.code
        print(e.code, file=sys.stderr)   # sys.exit("message") idiom
        return 1
    except MPIError as e:
        print(f"tpurun: job failed: {e}", file=sys.stderr)
        return getattr(e, "code", 1) or 1
    except BaseException as e:
        print(f"tpurun: job failed: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
