"""Version shims for the jax API surface this package targets.

The code base is written against the modern spelling ``jax.shard_map(f,
mesh=..., in_specs=..., out_specs=..., check_vma=...)``.  Older jax
releases (< 0.5) only ship ``jax.experimental.shard_map.shard_map`` with the
replication check spelled ``check_rep``.  Importing this module arranges for
a keyword-translating alias to appear at ``jax.shard_map`` when the
top-level name is missing, so the rest of the package (and its
tests/benchmarks, which import ``tpu_mpi`` before tracing) runs unmodified
on either generation.

``import tpu_mpi`` deliberately does not import jax (keeps the CLI/launcher
import light), so the shim installs lazily: immediately when jax is already
loaded, otherwise from a one-shot meta-path hook that fires as ``import
jax`` completes.  Deliberately tiny: one attribute, added only when absent,
delegating to the same underlying transform — not a reimplementation.
"""

from __future__ import annotations

import functools
import importlib.abc
import importlib.util
import sys


def _install_shims(jax) -> None:
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _legacy

        @functools.wraps(_legacy)
        def shard_map(f, /, *args, **kw):
            if "check_vma" in kw:      # renamed from check_rep in newer jax
                kw["check_rep"] = kw.pop("check_vma")
            return _legacy(f, *args, **kw)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # pre-0.5 spelling: core.axis_frame yields the static size of a
            # bound axis (an int at trace time, same as lax.axis_size)
            frame = jax.core.axis_frame(axis_name)
            return frame if isinstance(frame, int) else frame.size

        jax.lax.axis_size = axis_size


class _ShimLoader(importlib.abc.Loader):
    """Delegating loader that runs the shim after jax finishes executing."""

    def __init__(self, inner):
        self._inner = inner

    def create_module(self, spec):
        return self._inner.create_module(spec)

    def exec_module(self, module):
        self._inner.exec_module(module)
        _install_shims(module)


class _JaxImportHook(importlib.abc.MetaPathFinder):
    def find_spec(self, name, path=None, target=None):
        if name != "jax":
            return None
        sys.meta_path.remove(self)     # one-shot; also breaks the recursion
        spec = importlib.util.find_spec("jax")
        if spec is not None and spec.loader is not None:
            spec.loader = _ShimLoader(spec.loader)
        return spec


def ensure() -> None:
    """Install the shim now (if jax is loaded) or on jax import."""
    jax = sys.modules.get("jax")
    if jax is not None:
        _install_shims(jax)
    elif not any(isinstance(f, _JaxImportHook) for f in sys.meta_path):
        sys.meta_path.insert(0, _JaxImportHook())


ensure()
