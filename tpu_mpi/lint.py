"""``python -m tpu_mpi.lint file.py dir/ …`` — the static communication
lint CLI (docs/analysis.md). Thin shim over :mod:`tpu_mpi.analyze.lint`."""

from .analyze.lint import lint_paths, lint_source, main

__all__ = ["lint_paths", "lint_source", "main"]

if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
