"""Point-to-point messaging: Send/Recv, nonblocking requests, probes, waits.

Reference: /root/reference/src/pointtopoint.jl — Status (:5-79), Request
(:96-99), Probe (:121-127), Iprobe (:138-148), Get_count (:160-167), Send
(:179-200), serialized send (:208-211), Isend (:226-252), Recv!/Recv/recv
(:271-318), Irecv!/irecv (:333-358), Sendrecv! (:376-393), Wait!/Test!/
Waitall!/Testall!/Waitany!/Testany!/Waitsome!/Testsome!/Cancel! (:404-681).

TPU mapping (SURVEY.md §2.3): the *semantic* path runs through the host
matching engine (tpu_mpi._runtime.Mailbox) — tags, ANY_SOURCE/ANY_TAG,
non-overtaking order, Probe on unexpected messages, all the dynamic behavior
XLA's static SPMD model cannot express. Sends are buffered (snapshot at post
time; device arrays are immutable so the reference *is* the snapshot) and
complete immediately; receives are matched by the engine and complete on
Wait/Test in the receiving rank's thread, which also owns device placement.
The compiled neighbor-exchange path (``ppermute``-shaped, static patterns)
lives in ``tpu_mpi.xla``.

Indices returned by Waitany/Waitsome are 0-based (Python), where the
reference's are 1-based (Julia).
"""

from __future__ import annotations

import pickle
import time

import numpy as np
from typing import Any, Optional, Sequence

from . import serialization as _serialization

from ._runtime import (ANY_SOURCE, ANY_TAG, PROC_NULL, Mailbox, Message,
                       PendingRecv, require_env)
from .buffers import (element_count, extract_array, is_wire_snapshot,
                      to_wire, write_flat)
from .comm import Comm
from .datatypes import Datatype, to_datatype
from . import error as _ec
from . import perfvars as _pv
from .analyze import events as _ev
from .error import MPIError, TruncationError

_POLL = 0.001


class Status:
    """Completion metadata of a receive (src/pointtopoint.jl:5-79)."""

    __slots__ = ("source", "tag", "error", "count", "dtype")

    def __init__(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
                 error: int = 0, count: int = 0, dtype: Any = None):
        self.source = source
        self.tag = tag
        self.error = error
        self.count = count
        self.dtype = dtype

    def __repr__(self) -> str:
        return f"Status(source={self.source}, tag={self.tag}, count={self.count})"


STATUS_EMPTY = Status()


def Get_source(status: Status) -> int:
    return status.source


def Get_tag(status: Status) -> int:
    return status.tag


def Get_error(status: Status) -> int:
    return status.error


def Get_count(status: Status, T: Any = None) -> int:
    """Element count of the message in units of T (src/pointtopoint.jl:160-167)."""
    if T is None or status.dtype is None:
        return status.count
    want = to_datatype(T)
    have = status.dtype
    nbytes = status.count * have.size_bytes
    return nbytes // want.size_bytes


def _status_of(msg: Message) -> Status:
    return Status(source=msg.src, tag=msg.tag, count=msg.count, dtype=msg.dtype)


class Request:
    """Handle for a nonblocking operation (src/pointtopoint.jl:96-99).

    Holds a reference to the live buffer (the reference roots it against GC;
    here it also marks where a completed receive must be delivered). A send
    request is complete at creation (buffered send). REQUEST_NULL is modeled
    by a fresh inactive Request.
    """

    __slots__ = ("kind", "buffer", "status", "_pending", "_mailbox", "_count",
                 "_done", "_inactive", "_trace_isend", "_trace_comm",
                 "_trace_want")

    def __init__(self, kind: str = "null", buffer: Any = None,
                 pending: Optional[PendingRecv] = None, mailbox=None,
                 count: Optional[int] = None, status: Optional[Status] = None):
        self.kind = kind              # "send" | "recv" | "null"
        self.buffer = buffer
        self.status = status
        self._pending = pending
        self._mailbox = mailbox
        self._count = count
        # tpu_mpi.analyze hooks, populated only while tracing: the Isend
        # buffer checksum (T206) and the comm a traced Irecv records against.
        self._trace_isend = None
        self._trace_comm = None
        self._trace_want = None       # posted (src, tag) of a traced Irecv
        self._done = kind in ("send", "null")
        # True once the completion has been surfaced to the caller: the
        # request then behaves like MPI_REQUEST_NULL (libmpi writes the null
        # handle back on completion; Waitany/Waitsome must not return it again).
        self._inactive = kind == "null"

    # -- completion machinery ------------------------------------------------
    def _deliver(self) -> None:
        """Move a matched message into the user buffer (receiver's thread)."""
        pr = self._pending
        assert pr is not None and pr.msg is not None
        msg = pr.msg
        if self.buffer is not None:
            n = element_count(self.buffer)
            if msg.count > (self._count if self._count is not None else n):
                raise TruncationError(
                    f"message of {msg.count} elements truncated to {n}")
            write_flat(self.buffer, msg.payload, msg.count)
        self.status = _status_of(msg)
        self._done = True
        if self._trace_comm is not None:
            if _ev.enabled():
                want, wtag = self._trace_want or (msg.src, msg.tag)
                _ev.record_recv(self._trace_comm, msg, op="Irecv",
                                want=None if want == ANY_SOURCE else want,
                                wtag=None if wtag == ANY_TAG else wtag)
            if _pv.enabled():
                _pv.add_recv(self._trace_comm,
                             getattr(msg.payload, "nbytes", 0) or 0)

    def test(self) -> bool:
        """Nonblocking completion check; delivers on match."""
        if self._done:
            return True
        if self.kind == "recv":
            assert self._mailbox is not None and self._pending is not None
            if self._mailbox.test_recv(self._pending):
                if self._pending.cancelled and self._pending.msg is None:
                    self.buffer = None
                    self.status = STATUS_EMPTY
                    self._done = True
                else:
                    self._deliver()
                return True
            return False
        return self._done

    def wait(self) -> Status:
        """Block until complete; delivers the payload."""
        if self._inactive:
            return self.status or STATUS_EMPTY
        if not self._done and self.kind == "recv":
            assert self._mailbox is not None and self._pending is not None
            bev = None
            pv_on = _pv.enabled()
            t0 = _pv.monotonic() if pv_on else 0.0
            if self._trace_comm is not None:
                if _ev.enabled():
                    pr = self._pending
                    bev = _ev.blocked_event(
                        self._trace_comm, "recv", "Wait(Irecv)",
                        peer=None if pr.src == ANY_SOURCE else pr.src,
                        tag=pr.tag)
                    _ev.set_blocked(self._mailbox.ctx, bev)
            try:
                msg = self._mailbox.wait_recv(self._pending)
            finally:
                if bev is not None:
                    _ev.clear_blocked(self._mailbox.ctx, bev)
                if pv_on:
                    _pv.add_wait(_pv.monotonic() - t0, comm=self._trace_comm)
            if msg is None:          # cancelled (src/pointtopoint.jl:677-681)
                self.buffer = None
                self.status = STATUS_EMPTY
                self._done = True
            else:
                self._deliver()
        self._done = True
        return self._consume()

    def _consume(self) -> Status:
        """Surface the completion: clear the buffer root, go inactive."""
        st = self.status or STATUS_EMPTY
        if self._trace_isend is not None:
            # T206: re-checksum the Isend buffer before the root is cleared
            from ._runtime import current_env
            env = current_env()
            if env is not None:
                _ev.check_isend(env[0], self)
        self.buffer = None           # request deallocation clears the root
        self._inactive = True
        return st

    @property
    def active(self) -> bool:
        return not self._inactive

    def cancel(self) -> None:
        if self.kind == "recv" and not self._done:
            assert self._mailbox is not None and self._pending is not None
            self._mailbox.cancel(self._pending)

    def __repr__(self) -> str:
        return f"<Request {self.kind} done={self._done}>"


REQUEST_NULL = Request()


def _resolve(comm: Comm, comm_rank: int) -> int:
    return comm.world_rank_of(comm_rank)


def _my_mailbox(comm: Comm):
    ctx, world_rank = require_env()
    return ctx.mailboxes[world_rank]


def _post(comm: Comm, dest: int, tag: int, payload: Any, count: int,
          dtype: Optional[Datatype], kind: str, block: bool = False,
          mb: Any = None, ctx: Any = None, ubuf: Any = None) -> None:
    if ctx is None:                      # _send_typed already resolved it
        ctx, _ = require_env()
    ctx.check_failure()
    if ctx.failed_ranks or ctx.revoked_cids:   # fault path is pay-for-use
        ctx.check_fault(comm.cid)
    my_rank = comm.rank()
    # no seq stamp here: thread-tier delivery is atomic with ordering (one
    # mailbox lock), so there is nothing to check and the hot path stays
    # config-free; the wire proxy stamps under its own lock (backend.py)
    # tuple tags carry internal lanes (partitioned traffic: ("part", tag));
    # user tags stay ints
    msg = Message(my_rank,
                  tag if isinstance(tag, tuple) else int(tag),
                  comm.cid, payload, count, dtype, kind)
    if mb is None:                       # _send_typed already resolved it
        mb = ctx.mailboxes[_resolve(comm, dest)]
    traced = _ev.enabled()
    pv_on = _pv.enabled()
    t0 = _pv.monotonic() if pv_on else 0.0
    if traced:
        opname = (("Send" if block else "Isend") if kind == "typed"
                  else ("send" if block else "isend"))
        _ev.record_send(comm, dest, tag, count, dtype, op=opname,
                        buf=ubuf if ubuf is not None else payload)
    if block and hasattr(mb, "post_blocking"):
        # Flow control for blocking sends. Thread tier: admission-checked
        # against the destination queue under its lock. Multi-process tier:
        # choke/unchoke credit frames from the receiver pause this sender
        # while its unexpected queue is over the high-water mark.
        if traced:
            bev = _ev.blocked_event(comm, "send", opname, peer=dest, tag=tag)
            _ev.set_blocked(ctx, bev)
            try:
                mb.post_blocking(msg, "Send")
            finally:
                _ev.clear_blocked(ctx, bev)
        else:
            mb.post_blocking(msg, "Send")
    else:
        mb.post(msg)
    if pv_on:
        nb = getattr(payload, "nbytes", None)
        if nb is None:
            nb = len(payload) if isinstance(payload, (bytes, bytearray)) else 0
        _pv.add_send(comm, int(nb),
                     wait_ns=int((_pv.monotonic() - t0) * 1e9) if block else 0)


# ---------------------------------------------------------------------------
# Blocking / nonblocking send
# ---------------------------------------------------------------------------

def _send_typed(buf: Any, dest: int, tag: int, comm: Comm, block: bool) -> None:
    arr0 = extract_array(buf)
    if arr0 is None:
        raise MPIError(f"not a communication buffer: {type(buf).__name__}",
                       code=_ec.ERR_BUFFER)
    count = int(arr0.size)
    if isinstance(buf, np.ndarray) and is_wire_snapshot(buf):
        # already a private to_wire snapshot (Sendrecv_replace /
        # Isendrecv_replace made it): re-snapshotting would just copy again
        _post(comm, dest, tag, buf, count, to_datatype(buf.dtype), "typed",
              block=block, ubuf=arr0)
        return
    ctx, _ = require_env()
    mb = ctx.mailboxes[_resolve(comm, dest)]
    if not isinstance(mb, Mailbox):
        # Remote destination: the frame is FULLY off this buffer before the
        # call returns — tm_send/writev blocks until written, the shm lane
        # copies into its segment, the pickle lane serializes — for both
        # blocking Send and buffered Isend. The defensive to_wire snapshot
        # would be a second copy of every large payload (it halved the
        # shm-lane bandwidth); pass the user's array straight to the codec.
        # Same-process destinations still snapshot: there the payload
        # object itself outlives the call inside the peer's mailbox.
        if isinstance(arr0, np.ndarray):
            _post(comm, dest, tag, arr0, count, to_datatype(arr0.dtype),
                  "typed", block=block, mb=mb, ctx=ctx, ubuf=arr0)
            return
    arr = to_wire(buf, count)
    _post(comm, dest, tag, arr, count, to_datatype(arr.dtype), "typed",
          block=block, mb=mb, ctx=ctx, ubuf=arr0)


def Send(buf: Any, dest: int, tag: int, comm: Comm) -> None:
    """Blocking typed send (src/pointtopoint.jl:179-200); scalars welcome.

    The payload is snapshotted at call time; the call returns once the
    destination's unexpected queue has room (flow control — the rendezvous
    analog; small/first messages complete immediately, libmpi-eager style)."""
    if dest == PROC_NULL:
        return
    _send_typed(buf, dest, tag, comm, block=True)


def Isend(buf: Any, dest: int, tag: int, comm: Comm) -> Request:
    """Nonblocking send (src/pointtopoint.jl:226-239); completes immediately
    — buffered semantics, never subject to the blocking-send flow control
    (an Isend that blocked could deadlock MPI-legal exchange patterns)."""
    if dest == PROC_NULL:
        return Request("null", status=STATUS_EMPTY)
    _send_typed(buf, dest, tag, comm, block=False)
    req = Request("send", buffer=buf, status=STATUS_EMPTY)
    if _ev.enabled():
        _ev.note_isend(req, comm, buf)
    return req


def send(obj: Any, dest: int, tag: int, comm: Comm) -> None:
    """Serialized-object send (src/pointtopoint.jl:208-211); blocking, so
    subject to the same flow control as Send."""
    _send_obj(obj, dest, tag, comm, block=True)


def _send_obj(obj: Any, dest: int, tag: int, comm: Comm, block: bool) -> None:
    if dest == PROC_NULL:
        return
    try:
        # closures/lambdas/local classes travel by value on every tier
        # (tpu_mpi.serialization; ref ships closures between processes,
        # src/MPI.jl:9-18)
        data = _serialization.dumps(obj)
    except Exception:
        # In-process transport: truly unserializable objects (sockets,
        # locks) travel by reference (the multi-process mailbox proxy
        # rejects this kind with a clear error — no shared address space).
        _post(comm, dest, tag, obj, 0, None, "objref", block=block)
        return
    _post(comm, dest, tag, data, len(data), None, "object", block=block)


def isend(obj: Any, dest: int, tag: int, comm: Comm) -> Request:
    """Nonblocking serialized send (src/pointtopoint.jl:249-252); buffered,
    never blocks (see Isend)."""
    _send_obj(obj, dest, tag, comm, block=False)
    return Request("send", status=STATUS_EMPTY)


# ---------------------------------------------------------------------------
# Blocking / nonblocking receive
# ---------------------------------------------------------------------------

def Recv(buf_or_type: Any, src: int, tag: int, comm: Comm,
         status: Optional[Status] = None):
    """``Recv(buf, src, tag, comm) -> Status`` fills an existing buffer
    (ref ``Recv!`` :271-281); ``Recv(T, src, tag, comm) -> (value, Status)``
    receives one scalar of type T (:296-302).

    ``status``: a caller-owned Status to fill IN PLACE and return instead of
    allocating a fresh one per call (mpi4py's ``status=`` shape) — the
    tight-receive-loop lane."""
    if isinstance(buf_or_type, type) or isinstance(buf_or_type, Datatype):
        import numpy as np
        dt = to_datatype(buf_or_type)
        tmp = np.zeros(1, dtype=dt.np_dtype)
        st = Recv(tmp, src, tag, comm, status)
        return (tmp[0].item() if dt.np_dtype.fields is None else tmp[0]), st
    if src == PROC_NULL:
        return Status(source=PROC_NULL, tag=ANY_TAG, count=0)
    # inline blocking path (no Request object): match-or-wait in one
    # mailbox lock entry (direct-drain capable) — the small-message
    # latency lane (VERDICT r3 #4, r4 #5)
    mb = _my_mailbox(comm)
    pv_on = _pv.enabled()
    t0 = _pv.monotonic() if pv_on else 0.0
    if _ev.enabled():
        ctx, _ = require_env()
        bev = _ev.blocked_event(comm, "recv", "Recv",
                                peer=None if src == ANY_SOURCE else src,
                                tag=tag)
        _ev.set_blocked(ctx, bev)
        try:
            msg = mb.recv_blocking(int(src), int(tag), comm.cid)
        finally:
            _ev.clear_blocked(ctx, bev)
        _ev.record_recv(comm, msg, op="Recv",
                        want=None if src == ANY_SOURCE else src,
                        wtag=None if tag == ANY_TAG else int(tag))
    else:
        msg = mb.recv_blocking(int(src), int(tag), comm.cid)
    assert msg is not None            # blocking Recv exposes no cancel handle
    if pv_on and msg is not None:
        _pv.add_recv(comm, getattr(msg.payload, "nbytes", 0) or 0,
                     wait_ns=int((_pv.monotonic() - t0) * 1e9))
    n = element_count(buf_or_type)
    if msg.count > n:
        raise TruncationError(
            f"message of {msg.count} elements truncated to {n}")
    write_flat(buf_or_type, msg.payload, msg.count)
    if status is not None:
        status.source = msg.src
        status.tag = msg.tag
        status.error = 0
        status.count = msg.count
        status.dtype = msg.dtype
        return status
    return _status_of(msg)


def Irecv(buf: Any, src: int, tag: int, comm: Comm) -> Request:
    """Nonblocking receive into buf (ref ``Irecv!`` :333-346)."""
    if src == PROC_NULL:
        return Request("null", status=Status(source=PROC_NULL, tag=ANY_TAG))
    mb = _my_mailbox(comm)
    pr = mb.post_recv(int(src), int(tag), comm.cid)
    req = Request("recv", buffer=buf, pending=pr, mailbox=mb,
                  count=element_count(buf))
    # pvars ride the same comm backref tracing uses (every consumer of
    # _trace_comm re-gates on its own enabled() before acting on it)
    if _ev.enabled() or _pv.enabled():
        req._trace_comm = comm
        req._trace_want = (int(src), int(tag))
    return req


def recv(src: int, tag: int, comm: Comm):
    """Blocking serialized-object receive -> (obj, Status)
    (src/pointtopoint.jl:312-318, via Probe + Get_count)."""
    if src == PROC_NULL:
        return None, Status(source=PROC_NULL, tag=ANY_TAG, count=0)
    mb = _my_mailbox(comm)
    if _ev.enabled():
        ctx, _ = require_env()
        bev = _ev.blocked_event(comm, "recv", "recv",
                                peer=None if src == ANY_SOURCE else src,
                                tag=tag)
        _ev.set_blocked(ctx, bev)
        try:
            msg = mb.recv_blocking(int(src), int(tag), comm.cid)
        finally:
            _ev.clear_blocked(ctx, bev)
        _ev.record_recv(comm, msg, op="recv",
                        want=None if src == ANY_SOURCE else src,
                        wtag=None if tag == ANY_TAG else int(tag))
    else:
        msg = mb.recv_blocking(int(src), int(tag), comm.cid)
    assert msg is not None
    return _object_of(msg), _status_of(msg)


def irecv(src: int, tag: int, comm: Comm):
    """Nonblocking object receive -> (flag, obj|None, Status|None)
    (src/pointtopoint.jl:349-358, via Iprobe)."""
    if src == PROC_NULL:
        return (True, None, Status(source=PROC_NULL, tag=ANY_TAG, count=0))
    mb = _my_mailbox(comm)
    msg = mb.probe(int(src), int(tag), comm.cid, block=False)
    if msg is None:
        return (False, None, None)
    pr = mb.post_recv(msg.src, msg.tag, comm.cid)
    got = mb.wait_recv(pr)
    assert got is not None
    if _ev.enabled():
        _ev.record_recv(comm, got, op="irecv",
                        want=None if src == ANY_SOURCE else src,
                        wtag=None if tag == ANY_TAG else int(tag))
    return (True, _object_of(got), _status_of(got))


def _object_of(msg: Message) -> Any:
    if msg.kind == "object":
        return pickle.loads(msg.payload)
    if msg.kind == "objref":
        return msg.payload
    raise MPIError("typed message received with object API; use Recv")


def Sendrecv(sendbuf: Any, dest: int, sendtag: int,
             recvbuf: Any, src: int, recvtag: int, comm: Comm) -> Status:
    """Combined send+receive (ref ``Sendrecv!`` :376-393); deadlock-safe:
    the receive posts first, and the flow-controlled Send always admits a
    message that matches a posted receive."""
    rreq = Irecv(recvbuf, src, recvtag, comm) if src != PROC_NULL else None
    Send(sendbuf, dest, sendtag, comm)
    if rreq is None:
        return Status(source=PROC_NULL, tag=ANY_TAG, count=0)
    return rreq.wait()


# ---------------------------------------------------------------------------
# Probe
# ---------------------------------------------------------------------------

def Probe(src: int, tag: int, comm: Comm) -> Status:
    """Block until a matching message is enqueued (src/pointtopoint.jl:121-127)."""
    if src == PROC_NULL:
        return Status(source=PROC_NULL, tag=ANY_TAG, count=0)
    mb = _my_mailbox(comm)
    if _ev.enabled():
        ctx, _ = require_env()
        bev = _ev.blocked_event(comm, "recv", "Probe",
                                peer=None if src == ANY_SOURCE else src,
                                tag=tag)
        _ev.set_blocked(ctx, bev)
        try:
            msg = mb.probe(int(src), int(tag), comm.cid, block=True)
        finally:
            _ev.clear_blocked(ctx, bev)
    else:
        msg = mb.probe(int(src), int(tag), comm.cid, block=True)
    assert msg is not None
    return _status_of(msg)


def Iprobe(src: int, tag: int, comm: Comm):
    """Nonblocking probe -> (flag, Status|None) (src/pointtopoint.jl:138-148)."""
    if src == PROC_NULL:
        return (True, Status(source=PROC_NULL, tag=ANY_TAG, count=0))
    mb = _my_mailbox(comm)
    msg = mb.probe(int(src), int(tag), comm.cid, block=False)
    if msg is None:
        return (False, None)
    return (True, _status_of(msg))


# ---------------------------------------------------------------------------
# Completion: Wait/Test families (src/pointtopoint.jl:404-681)
# ---------------------------------------------------------------------------

def Wait(req: Request) -> Status:
    """Block until req completes (ref ``Wait!`` :404-416)."""
    return req.wait()


def Test(req: Request):
    """(done, Status|None) without blocking (ref ``Test!`` :426-442).
    An inactive (already-consumed / null) request tests as done with an
    empty status, like MPI_REQUEST_NULL."""
    if not req.active:
        return (True, req.status or STATUS_EMPTY)
    if req.test():
        return (True, req._consume())
    return (False, None)


def Waitall(reqs: Sequence[Request]) -> list[Status]:
    """Block until all complete (ref ``Waitall!`` :453-471). A run of
    fast-armed persistent collective rounds completes through batched
    rendezvous submission first — one channel wakeup for the whole run
    (``overlap.waitall_flush``) — before the per-request waits."""
    from .overlap import waitall_flush
    waitall_flush(reqs)
    return [r.wait() for r in reqs]


def Testall(reqs: Sequence[Request]):
    """(all_done, [Status]) — only consumes requests if all are done
    (ref ``Testall!`` :484-506)."""
    if all((not r.active) or r.test() for r in reqs):
        return (True, [r._consume() if r.active else (r.status or STATUS_EMPTY)
                       for r in reqs])
    return (False, [])


def _poll_ready(reqs: Sequence[Request]) -> list[int]:
    """Spin (with failure checks) until ≥1 *active* request completes.
    Returns [] when no request is active; raises DeadlockError after the
    runtime's deadlock timeout like every other blocking wait."""
    from ._runtime import deadlock_timeout, raise_deadlock
    ctx, _ = require_env()
    limit = deadlock_timeout()
    deadline = time.monotonic() + limit
    while True:
        if not any(r.active for r in reqs):
            return []
        ready = [i for i, r in enumerate(reqs) if r.active and r.test()]
        if ready:
            return ready
        ctx.check_failure()
        if time.monotonic() > deadline:
            raise_deadlock(
                ctx, f"deadlock suspected: blocked >{limit}s in Waitany/Waitsome")
        time.sleep(_POLL)


def Waitany(reqs: Sequence[Request]):
    """(index, Status) of one newly-completed request, 0-based; (None,
    STATUS_EMPTY) when no request is active (ref ``Waitany!`` :520-541,
    which is 1-based and maps MPI_UNDEFINED to 0)."""
    ready = _poll_ready(reqs)
    if not ready:
        return (None, STATUS_EMPTY)
    i = ready[0]
    return (i, reqs[i]._consume())


def Testany(reqs: Sequence[Request]):
    """(found, index|None, Status|None); (True, None, STATUS_EMPTY) when no
    request is active (ref ``Testany!`` :557-581)."""
    if not any(r.active for r in reqs):
        return (True, None, STATUS_EMPTY)
    for i, r in enumerate(reqs):
        if r.active and r.test():
            return (True, i, r._consume())
    return (False, None, None)


def Waitsome(reqs: Sequence[Request]):
    """(indices, [Status]) of ≥1 newly-completed requests; ([], []) when no
    request is active (ref ``Waitsome!`` :594-624)."""
    ready = _poll_ready(reqs)
    return (ready, [reqs[i]._consume() for i in ready])


def Testsome(reqs: Sequence[Request]):
    """(indices, [Status]) of currently-completed active requests
    (ref ``Testsome!`` :635-665)."""
    ready = [i for i, r in enumerate(reqs) if r.active and r.test()]
    return (ready, [reqs[i]._consume() for i in ready])


def Cancel(req: Request) -> None:
    """Cancel a pending receive (ref ``Cancel!`` :677-681)."""
    req.cancel()


# ---------------------------------------------------------------------------
# Persistent requests (MPI_Send_init / MPI_Recv_init / MPI_Start — absent
# from the reference v0.14.2; provided beyond parity). A persistent request
# binds the (buffer, peer, tag, comm) pattern once and Start re-arms it per
# round — the MPI API shape for fixed-pattern exchanges (halo loops,
# pipeline hops). Semantics only: each Start performs a full Isend/Irecv
# under the hood, so there is no setup-amortization fast path here (an MPI
# implementation MAY optimize persistent rounds; this one does not yet).
# ---------------------------------------------------------------------------

class Prequest:
    """Persistent communication request.

    Duck-types the Request completion protocol, so the whole Wait/Test
    family accepts it. Completion returns it to INACTIVE-BUT-REUSABLE
    (MPI semantics: a completed persistent request is not freed); call
    :func:`Start` to re-arm it. The bound buffer stays attached across
    rounds."""

    def __init__(self, make, kind: str, buffer: Any):
        self._make = make           # () -> a live one-shot Request
        self._inner: Optional[Request] = None
        self.kind = kind            # "psend" | "precv"
        self.buffer = buffer
        self.status: Optional[Status] = None

    def start(self) -> "Prequest":
        if self._inner is not None and self._inner.active:
            raise MPIError("Start on an already-active persistent request",
                           code=_ec.ERR_REQUEST)
        self._inner = self._make()
        return self

    @property
    def active(self) -> bool:
        return self._inner is not None and self._inner.active

    def test(self) -> bool:
        if self._inner is None:
            return True
        return self._inner.test()

    def wait(self) -> Status:
        if self._inner is None:
            return self.status or STATUS_EMPTY
        self.status = self._inner.wait()
        self._inner = None          # inactive, ready for the next Start
        return self.status

    def _consume(self) -> Status:
        if self._inner is None:
            return self.status or STATUS_EMPTY
        self.status = self._inner._consume() if self._inner.active \
            else (self._inner.status or STATUS_EMPTY)
        self._inner = None
        return self.status

    def cancel(self) -> None:
        if self._inner is not None:
            self._inner.cancel()

    def __repr__(self) -> str:
        return f"<Prequest {self.kind} active={self.active}>"


def Send_init(buf: Any, dest: int, tag: int, comm: Comm) -> Prequest:
    """Create an inactive persistent send of ``buf`` to ``dest``
    (MPI_Send_init). Arm with :func:`Start`; each round snapshots the
    buffer's CURRENT contents (update it between rounds freely)."""
    def make():
        return Isend(buf, dest, tag, comm)
    return Prequest(make, "psend", buf)


def Recv_init(buf: Any, src: int, tag: int, comm: Comm) -> Prequest:
    """Create an inactive persistent receive into ``buf``
    (MPI_Recv_init). Arm with :func:`Start`."""
    def make():
        return Irecv(buf, src, tag, comm)
    return Prequest(make, "precv", buf)


def Start(req: Prequest) -> Prequest:
    """Arm a persistent or partitioned request (MPI_Start) — P2P
    (Send_init/Recv_init), partitioned (Psend_init/Precv_init), or
    persistent collective (Allreduce_init/Bcast_init/Barrier_init,
    tpu_mpi.collective)."""
    if not hasattr(req, "start"):
        raise MPIError(code=_ec.ERR_REQUEST,
                       msg="Start requires a persistent/partitioned request "
                       "(Send_init/Recv_init/Psend_init/Precv_init/"
                       "Allreduce_init/Bcast_init/Barrier_init)")
    return req.start()


def Startall(reqs: Sequence[Prequest]) -> Sequence[Prequest]:
    """Arm several persistent requests (MPI_Startall). Persistent
    collectives must be started in the same order on every rank (the
    MPI-4 initiation-order rule); a single Startall list in matching
    order satisfies it."""
    for r in reqs:
        Start(r)
    return reqs


def Sendrecv_replace(buf: Any, dest: int, sendtag: int, src: int,
                     recvtag: int, comm: Comm) -> Status:
    """Combined send+receive through ONE buffer (MPI_Sendrecv_replace —
    absent from the reference v0.14.2; standard MPI-1). The outgoing data
    is snapshotted before the receive can overwrite it."""
    snap = to_wire(buf, element_count(buf))
    return Sendrecv(snap, dest, sendtag, buf, src, recvtag, comm)


def Isendrecv(sendbuf: Any, dest: int, sendtag: int,
              recvbuf: Any, src: int, recvtag: int, comm: Comm) -> Request:
    """Nonblocking combined send+receive (MPI-4 MPI_Isendrecv; beyond the
    reference). Returns ONE request that completes when the receive lands;
    the send side is buffered (Isend semantics) and needs no tracking."""
    rreq = Irecv(recvbuf, src, recvtag, comm) if src != PROC_NULL else \
        Request("null", status=Status(source=PROC_NULL, tag=ANY_TAG))
    if dest != PROC_NULL:
        _send_typed(sendbuf, dest, sendtag, comm, block=False)
    return rreq


def Isendrecv_replace(buf: Any, dest: int, sendtag: int, src: int,
                      recvtag: int, comm: Comm) -> Request:
    """Nonblocking combined send+receive through one buffer (MPI-4
    MPI_Isendrecv_replace). The outgoing data is snapshotted at call time."""
    snap = to_wire(buf, element_count(buf))
    return Isendrecv(snap, dest, sendtag, buf, src, recvtag, comm)


# ---------------------------------------------------------------------------
# Partitioned communication (MPI-4 §4.2 — far beyond the reference v0.14.2).
# A partitioned send binds one buffer split into N equal partitions; the
# application marks partitions ready as it produces them (Pready) and each
# ships immediately — the MPI API shape for compute/communication overlap
# that TPU pipelines use (a stage Preadys its microbatch slice as the next
# one computes). The receive side completes partition-by-partition
# (Parrived), so a consumer can start on early partitions while later ones
# are still in flight.
#
# Host-path realization: each partition travels as one ordinary message on
# the derived tag ("part", tag) — per-(src,dst,cid) FIFO plus the
# Start-after-Wait contract keeps rounds from interleaving, so no round
# counter is needed on the wire.
# ---------------------------------------------------------------------------

class PartitionedRequest:
    """Partitioned request (Psend_init / Precv_init). Duck-types the Request
    completion protocol, so the whole Wait/Test family accepts it. Like
    persistent requests, completion returns it to inactive-but-reusable."""

    def __init__(self, kind: str, buf: Any, partitions: int, peer: int,
                 tag: int, comm: Comm):
        n = element_count(buf)
        if partitions < 1 or n % partitions != 0:
            raise MPIError(f"buffer of {n} elements cannot split into "
                           f"{partitions} equal partitions",
                           code=_ec.ERR_COUNT)
        self.kind = kind            # "psend" | "precv"
        self.buffer = buf
        self.partitions = partitions
        self.plen = n // partitions
        self.peer = peer
        self.tag = ("part", int(tag))
        self.comm = comm
        self.status: Optional[Status] = None
        self._active = False
        # send side: which partitions were Pready'd this round
        self._ready: set[int] = set()
        # recv side: pending receives + arrived partition payloads
        self._pending: list = []
        self._arrived: dict[int, Any] = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "PartitionedRequest":
        if self._active:
            raise MPIError("Start on an already-active partitioned request",
                           code=_ec.ERR_REQUEST)
        self._active = True
        self._ready = set()
        self._arrived = {}
        if self.kind == "precv":
            mb = _my_mailbox(self.comm)
            self._pending = [
                mb.post_recv(int(self.peer), self.tag, self.comm.cid)
                for _ in range(self.partitions)]
        return self

    @property
    def active(self) -> bool:
        return self._active

    # -- send side -----------------------------------------------------------
    def pready(self, i: int) -> None:
        if self.kind != "psend":
            raise MPIError("Pready on a partitioned receive",
                           code=_ec.ERR_REQUEST)
        if not self._active:
            raise MPIError("Pready before Start", code=_ec.ERR_REQUEST)
        i = int(i)
        if not (0 <= i < self.partitions):
            raise MPIError(f"partition {i} out of range "
                           f"[0, {self.partitions})", code=_ec.ERR_ARG)
        if i in self._ready:
            raise MPIError(f"partition {i} already marked ready",
                           code=_ec.ERR_REQUEST)
        arr = extract_array(self.buffer)
        a, b = i * self.plen, (i + 1) * self.plen
        # snapshot ONLY partition i (partition data is read at Pready time,
        # not Start — the buffer may be filled partition-by-partition); a
        # whole-buffer ascontiguousarray here would copy N elements per
        # Pready and defeat the overlap purpose of partitioned sends
        if isinstance(arr, np.ndarray):
            part = (np.array(arr.reshape(-1)[a:b], copy=True)
                    if arr.flags.c_contiguous else np.asarray(arr.flat[a:b]))
        else:
            # device array: slice on device, transfer only the partition
            part = np.asarray(arr.reshape(-1)[a:b])
        _post(self.comm, self.peer, self.tag, (i, part), self.plen, None,
              "object", block=False)
        self._ready.add(i)

    # -- recv side -----------------------------------------------------------
    def _accept(self, payload) -> None:
        i, part = payload
        n = int(np.asarray(part).size)
        if n != self.plen:
            raise MPIError(
                f"partitioned transfer mismatch: sender partition holds {n} "
                f"elements, receiver expects {self.plen} — Psend_init and "
                f"Precv_init must describe the same partitioning",
                code=_ec.ERR_COUNT)
        self._arrived[int(i)] = part

    def _drain_arrivals(self) -> None:
        mb = _my_mailbox(self.comm)
        traced = _ev.enabled()
        still = []
        for pr in self._pending:
            if mb.test_recv(pr) and pr.msg is not None:
                if traced:
                    _ev.record_recv(self.comm, pr.msg, op="Precv")
                self._accept(pr.msg.payload)
            else:
                still.append(pr)
        self._pending = still

    def parrived(self, i: int) -> bool:
        if self.kind != "precv":
            raise MPIError("Parrived on a partitioned send",
                           code=_ec.ERR_REQUEST)
        self._drain_arrivals()
        if int(i) in self._arrived:
            self._deliver_one(int(i))
            return True
        return False

    def _deliver_one(self, i: int) -> None:
        part = self._arrived.get(i)
        if part is None or isinstance(part, bool):
            return
        from .buffers import write_range
        write_range(self.buffer, i * self.plen, np.asarray(part).reshape(-1))
        self._arrived[i] = True       # delivered marker

    # -- completion protocol (Wait/Test family) ------------------------------
    def test(self) -> bool:
        if not self._active:
            return True
        if self.kind == "psend":
            return len(self._ready) == self.partitions
        self._drain_arrivals()
        return len(self._arrived) == self.partitions

    def wait(self) -> Status:
        if not self._active:
            return self.status or STATUS_EMPTY
        ctx, _ = require_env()
        if self.kind == "psend":
            # completes once every partition was marked ready (they ship
            # eagerly at Pready time). Another thread may still be
            # producing partitions — poll with the deadlock budget.
            from ._runtime import deadlock_timeout
            deadline = time.monotonic() + deadlock_timeout()
            while len(self._ready) < self.partitions:
                ctx.check_failure()
                if time.monotonic() > deadline:
                    raise MPIError(
                        f"Wait on partitioned send with only "
                        f"{len(self._ready)}/{self.partitions} partitions "
                        f"marked ready", code=_ec.ERR_PENDING)
                time.sleep(0.0005)
            self.status = STATUS_EMPTY
        else:
            mb = _my_mailbox(self.comm)
            traced = _ev.enabled()
            cancelled = False
            for pr in self._pending:
                msg = mb.wait_recv(pr)
                if msg is None:               # receive was cancelled
                    cancelled = True
                    continue
                if traced:
                    _ev.record_recv(self.comm, msg, op="Precv")
                self._accept(msg.payload)
            self._pending = []
            if cancelled and len(self._arrived) < self.partitions:
                self.status = STATUS_EMPTY
                self._active = False
                return self.status
            for i in range(self.partitions):
                self._deliver_one(i)
            self.status = Status(source=int(self.peer), tag=self.tag[1],
                                 count=self.partitions * self.plen)
        self._active = False
        return self.status

    def _consume(self) -> Status:
        st = self.wait() if self._active else (self.status or STATUS_EMPTY)
        return st

    def cancel(self) -> None:
        mb = _my_mailbox(self.comm)
        for pr in self._pending:
            mb.cancel(pr)

    def __repr__(self) -> str:
        return (f"<PartitionedRequest {self.kind} "
                f"{self.partitions}x{self.plen} active={self._active}>")


def Psend_init(buf: Any, partitions: int, dest: int, tag: int,
               comm: Comm) -> PartitionedRequest:
    """Create an inactive partitioned send (MPI-4 MPI_Psend_init): ``buf``
    splits into ``partitions`` equal parts; after :func:`Start`, mark each
    with :func:`Pready` as its data becomes valid — it ships immediately."""
    return PartitionedRequest("psend", buf, int(partitions), dest, tag, comm)


def Precv_init(buf: Any, partitions: int, src: int, tag: int,
               comm: Comm) -> PartitionedRequest:
    """Create an inactive partitioned receive (MPI-4 MPI_Precv_init);
    :func:`Parrived` reports (and delivers) individual partitions before
    the whole request completes."""
    return PartitionedRequest("precv", buf, int(partitions), src, tag, comm)


def Pready(req: PartitionedRequest, i: int) -> None:
    """Mark partition ``i`` of an active partitioned send ready
    (MPI_Pready); the partition is transferred immediately."""
    req.pready(i)


def Pready_range(req: PartitionedRequest, lo: int, hi: int) -> None:
    """Mark partitions [lo, hi] ready (MPI_Pready_range; bounds inclusive
    per the MPI-4 binding)."""
    for i in range(int(lo), int(hi) + 1):
        req.pready(i)


def Parrived(req: PartitionedRequest, i: int) -> bool:
    """Whether partition ``i`` of an active partitioned receive has arrived
    (MPI_Parrived); an arrived partition is delivered into its slice of the
    receive buffer before this returns True."""
    return req.parrived(i)
