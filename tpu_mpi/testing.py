"""Test substrate: run SPMD programs on simulated ranks in one process.

Mirrors the reference's test driver (/root/reference/test/runtests.jl:28-45),
which launches every test file under ``mpiexec -n N``; here each test body runs
under :func:`tpu_mpi.spmd_run` on N rank-threads, with JAX on N fake XLA CPU
devices (``--xla_force_host_platform_device_count``, SURVEY.md §3.5) so the
same suite later runs unchanged on a real TPU slice.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import numpy as np

from ._runtime import spmd_run
from . import environment


DEFAULT_NPROCS = 4   # clamp(CPU_THREADS, 2, 4) in test/runtests.jl:20-21


def mpi_main(fn: Callable[[], Any]) -> Callable[[], Any]:
    """Wrap a test body in Init/Finalize like every reference test file."""
    @functools.wraps(fn)
    def body(*args: Any) -> Any:
        environment.Init()
        try:
            return fn(*args)
        finally:
            if not environment.Finalized():
                environment.Finalize()
    return body


def run_spmd(fn: Callable[[], Any], nprocs: int = DEFAULT_NPROCS, *,
             init: bool = True, args: tuple = (),
             timeout: Optional[float] = 120.0) -> list:
    """Run fn as an SPMD program on nprocs ranks; Init/Finalize automatically."""
    body = mpi_main(fn) if init else fn
    return spmd_run(body, nprocs, args=args, timeout=timeout)


def aeq(a: Any, b: Any) -> bool:
    """Array equality across the array-type registry (numpy / jax / DeviceBuffer)."""
    return bool(np.array_equal(np.asarray(a), np.asarray(b)))
