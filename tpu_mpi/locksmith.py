"""Runtime lock witness — the dynamic half of the concurrency analyzer.

The serve fabric (broker dispatch, procs-pool driver, elastic rebind,
infer scheduler, router splice threads) is a hand-rolled thread fabric;
``tpu_mpi.analyze.concurrency`` audits it statically, and this module
audits it live. With ``TPU_MPI_LOCKCHECK=1`` every named lock
construction site (:func:`make_lock` / :func:`make_rlock` /
:func:`make_condition`) returns a witness shim instead of the plain
``threading`` primitive. The witness

- records which locks each thread holds and where it acquired them,
- maintains the process-global acquisition-order graph, and raises a
  typed :class:`tpu_mpi.error.LockOrderError` the moment two threads
  establish *inverted* order — no thread has to actually deadlock,
- records **C401** (held-while-blocking) when a witnessed
  ``Condition.wait`` runs while the thread holds another witnessed lock,
- feeds the ``locks`` pvar block (``acquires`` / ``contended`` /
  ``max_held_ns`` per named lock — ``tpurun --stats``), and
- lands acquisition events for dispatch-named locks in the event IR
  (once :func:`bind_context` attaches a tracer) so ``analyze verify``
  can audit dispatch-section serialization (T215).

Pay-for-use like pvars: the gate is evaluated once, at lock
*construction* — with the knob off every factory returns the plain
``threading`` primitive and the steady-state cost is zero.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

from . import config
from .error import LockOrderError

_UNSET = object()
_enabled_cache: Tuple[Any, bool] = (_UNSET, False)
_stacks_cache: Tuple[Any, bool] = (_UNSET, False)


def enabled() -> bool:
    """Whether the witness is armed — cached on ``config.GENERATION`` so
    the per-construction cost of a disabled run is one tuple compare."""
    global _enabled_cache
    cached_gen, val = _enabled_cache
    if cached_gen == config.GENERATION:
        return val
    val = bool(config.load().lockcheck)
    _enabled_cache = (config.GENERATION, val)
    return val


def _stacks() -> bool:
    global _stacks_cache
    cached_gen, val = _stacks_cache
    if cached_gen == config.GENERATION:
        return val
    val = bool(config.load().lockcheck_stacks)
    _stacks_cache = (config.GENERATION, val)
    return val


# ---------------------------------------------------------------------------
# Witness state: held-lock registry (per thread, globally visible so the
# deadlock dump can render every thread), order graph with per-edge
# provenance, and the C401 record list.
# ---------------------------------------------------------------------------

_reg_lock = threading.Lock()
# thread ident -> (thread name, [ [witness, site, t_ns, count], ... ])
_held_by_thread: Dict[int, Tuple[str, list]] = {}
# order graph: name -> set of names acquired while `name` was held
_succ: Dict[str, set] = {}
# edge (outer, inner) -> (outer's acquisition site, inner's acquisition site)
# — the first observation's provenance, rendered into cycle reports
_edge_sites: Dict[Tuple[str, str], Tuple[str, str]] = {}
# C401 diagnostics (analyze.diagnostics.Diagnostic records)
_c401: List[Any] = []
# bound tracer context for event-IR recording (see bind_context)
_ctx: Any = None


def _site() -> str:
    """The acquisition site as a ``file:line`` chain — the caller's frame
    outside this module, or the full stack under TPU_MPI_LOCKCHECK_STACKS."""
    if _stacks():
        frames = traceback.extract_stack()[:-2]
        return " <- ".join(f"{f.filename}:{f.lineno}"
                           for f in reversed(frames[-8:]))
    f = sys._getframe(1)
    here = __file__
    while f is not None and f.f_code.co_filename == here:
        f = f.f_back
    if f is None:
        return "<unknown>"
    return f"{f.f_code.co_filename}:{f.f_lineno}"


def _held_entries() -> list:
    """This thread's held-lock entry list (created on first use)."""
    ident = threading.get_ident()
    with _reg_lock:
        row = _held_by_thread.get(ident)
        if row is None:
            row = _held_by_thread[ident] = (threading.current_thread().name,
                                            [])
        return row[1]


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """A lock-name path ``src -> ... -> dst`` in the order graph, or None."""
    if src == dst:
        return [src]
    seen = {src}
    parent: Dict[str, str] = {}
    frontier = [src]
    while frontier:
        nxt = []
        for a in frontier:
            for b in _succ.get(a, ()):
                if b in seen:
                    continue
                seen.add(b)
                parent[b] = a
                if b == dst:
                    path = [b]
                    while path[-1] != src:
                        path.append(parent[path[-1]])
                    path.reverse()
                    return path
                nxt.append(b)
        frontier = nxt
    return None


def _render_chain(path: List[str]) -> str:
    hops = []
    for a, b in zip(path, path[1:]):
        outer, inner = _edge_sites.get((a, b), ("<unknown>", "<unknown>"))
        hops.append(f"{a} (held from {outer}) -> {b} (acquired at {inner})")
    return "; ".join(hops)


def _check_order(inner: "_WitnessBase", inner_site: str, held: list) -> None:
    """Called with ``_reg_lock`` held, before blocking on ``inner``: add
    edges held-lock -> inner and raise LockOrderError on any inversion."""
    for entry in held:
        outer = entry[0]
        if outer is inner:
            continue
        a, b = outer.name, inner.name
        if b in _succ.get(a, ()):
            continue                      # edge already established
        back = _find_path(b, a)
        if back is not None:
            # provenance of the forward edge is THIS acquisition
            forward = f"{a} (held from {entry[1]}) -> " \
                      f"{b} (acquired at {inner_site})"
            raise LockOrderError(
                f"lock order inversion: acquiring {b!r} while holding "
                f"{a!r}, but the opposite order is already established\n"
                f"  this thread:        {forward}\n"
                f"  established order:  {_render_chain(back)}")
        _succ.setdefault(a, set()).add(b)
        _edge_sites[(a, b)] = (entry[1], inner_site)


def _record_event(name: str, op: str) -> None:
    """Land a witness event in the event IR when a tracer is bound and the
    lock is dispatch-named (the T215-relevant critical sections)."""
    if _ctx is None or "dispatch" not in name:
        return
    try:
        from .analyze import events as _ev
        _ev.record_serve(_ctx, op, lock=name)
    except Exception:
        pass


class _WitnessBase:
    """Shared acquire/release bookkeeping for Lock and RLock witnesses."""

    reentrant = False

    def __init__(self, name: str, inner):
        self.name = str(name)
        self._inner = inner

    # -- context manager ----------------------------------------------------
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked() if hasattr(self._inner, "locked") \
            else False

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        site = _site()
        held = _held_entries()
        with _reg_lock:
            mine = None
            if self.reentrant:
                for entry in held:
                    if entry[0] is self:
                        mine = entry
                        break
            if mine is None:
                _check_order(self, site, held)
        if mine is not None:
            # reentrant re-acquire: no order edges, no contention stats
            got = self._inner.acquire(blocking, timeout)
            if got:
                with _reg_lock:
                    mine[3] += 1
            return got
        contended = 0
        got = self._inner.acquire(False)
        if not got:
            contended = 1
            if not blocking:
                _note(self.name, acquires=0, contended=1)
                return False
            got = self._inner.acquire(True, timeout)
            if not got:
                _note(self.name, acquires=0, contended=1)
                return False
        t = time.monotonic_ns()
        with _reg_lock:
            held.append([self, site, t, 1])
        _note(self.name, acquires=1, contended=contended)
        _record_event(self.name, "lock_acquire")
        return True

    def release(self) -> None:
        held = _held_entries()
        held_ns = 0
        with _reg_lock:
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self:
                    held[i][3] -= 1
                    if held[i][3] == 0:
                        held_ns = time.monotonic_ns() - held[i][2]
                        del held[i]
                    break
            # a plain Lock may legally be released by a thread that never
            # acquired it (handoff); the witness just loses the hold time
        self._inner.release()
        if held_ns:
            _note(self.name, held_ns=held_ns)
            _record_event(self.name, "lock_release")


class LockWitness(_WitnessBase):
    """``threading.Lock`` shim with order-graph witnessing."""

    def __init__(self, name: str, inner=None):
        super().__init__(name, inner if inner is not None
                         else threading.Lock())


class RLockWitness(_WitnessBase):
    """``threading.RLock`` shim — reentrant acquires add no order edges."""

    reentrant = True

    def __init__(self, name: str, inner=None):
        super().__init__(name, inner if inner is not None
                         else threading.RLock())


class ConditionWitness:
    """``threading.Condition`` shim over a witnessed lock. ``wait`` drops
    the witness's held entry for the duration (the underlying condition
    releases the real lock) and records C401 when the waiting thread still
    holds *other* witnessed locks — that is held-while-blocking, the
    runtime twin of the static L113."""

    def __init__(self, name: str, lock: Optional[_WitnessBase] = None):
        self.name = str(name)
        self._wit = lock if lock is not None else LockWitness(name)
        self._cond = threading.Condition(self._wit._inner)

    # -- lock surface (delegates to the witness) ----------------------------
    def __enter__(self):
        self._wit.acquire()
        return self

    def __exit__(self, *exc):
        self._wit.release()
        return False

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._wit.acquire(blocking, timeout)

    def release(self) -> None:
        self._wit.release()

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def wait(self, timeout: Optional[float] = None) -> bool:
        held = _held_entries()
        saved = None
        with _reg_lock:
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] is self._wit:
                    saved = held.pop(i)
                    break
            others = [e for e in held if e[0] is not self._wit]
            if others:
                _note_c401(self.name, others)
        try:
            return self._cond.wait(timeout)
        finally:
            if saved is not None:
                saved[2] = time.monotonic_ns()   # hold restarts at wake
                with _reg_lock:
                    held.append(saved)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # CPython's Condition.wait_for, routed through our wait()
        endtime = None
        waittime = timeout
        result = predicate()
        while not result:
            if waittime is not None:
                if endtime is None:
                    endtime = time.monotonic() + waittime
                else:
                    waittime = endtime - time.monotonic()
                    if waittime <= 0:
                        break
            self.wait(waittime)
            result = predicate()
        return result


def _note(name: str, **counts: int) -> None:
    from . import perfvars
    perfvars.note_lock(name, **counts)


def _note_c401(cond_name: str, others: list) -> None:
    """Record one held-while-blocking observation (called with _reg_lock)."""
    from .analyze.diagnostics import Diagnostic
    site = _site()
    file, _, line = site.partition(" <- ")[0].rpartition(":")
    try:
        lineno = int(line)
    except ValueError:
        file, lineno = site, 0
    names = ", ".join(sorted({e[0].name for e in others}))
    _c401.append(Diagnostic(
        "C401",
        f"Condition {cond_name!r} waited while this thread held "
        f"{names}",
        file=file or "<unknown>", line=lineno,
        related=tuple(_entry_related(e) for e in others)))


def _entry_related(entry) -> tuple:
    site = entry[1].partition(" <- ")[0]
    file, _, line = site.rpartition(":")
    try:
        lineno = int(line)
    except ValueError:
        file, lineno = site, 0
    return (file or "<unknown>", lineno, f"holding {entry[0].name!r}")


# ---------------------------------------------------------------------------
# Factories — the ONLY gate. With lockcheck off these return the plain
# threading primitives; nothing else in this module runs.
# ---------------------------------------------------------------------------

def make_lock(name: str):
    """A named mutex: ``threading.Lock()`` normally, a witness when armed."""
    if not enabled():
        return threading.Lock()
    return LockWitness(name)


def make_rlock(name: str):
    """A named reentrant mutex (see :func:`make_lock`)."""
    if not enabled():
        return threading.RLock()
    return RLockWitness(name)


def make_condition(name: str, lock=None):
    """A named condition variable over ``lock`` (or a fresh mutex). Pairs
    with locks from :func:`make_lock` / :func:`make_rlock`: hand the same
    object in and wait/notify share the witness's bookkeeping."""
    if isinstance(lock, _WitnessBase):
        return ConditionWitness(name, lock)
    if not enabled():
        return threading.Condition(lock)
    if lock is not None:
        # a plain lock constructed before the knob flipped: stay plain —
        # witnessing only the condition would corrupt held bookkeeping
        return threading.Condition(lock)
    return ConditionWitness(name)


# ---------------------------------------------------------------------------
# Introspection: dumps for DeadlockError / analyze verify / tests.
# ---------------------------------------------------------------------------

def bind_context(ctx) -> None:
    """Attach a tracer context: dispatch-named lock transitions land in the
    event IR from here on (kind ``serve``, ops ``lock_acquire`` /
    ``lock_release``)."""
    global _ctx
    _ctx = ctx


def armed() -> bool:
    """Whether any witness state exists (locks were built while enabled)."""
    with _reg_lock:
        return bool(_succ or _held_by_thread or _c401)


def c401_diagnostics() -> list:
    """C401 held-while-blocking observations so far (Diagnostic records)."""
    with _reg_lock:
        return list(_c401)


def order_graph() -> Dict[str, tuple]:
    """The observed acquisition-order graph as ``{outer: (inner, ...)}``."""
    with _reg_lock:
        return {a: tuple(sorted(bs)) for a, bs in sorted(_succ.items())}


def witness_report() -> str:
    """Per-thread held-lock sets with acquisition sites — appended to
    deadlock dumps when the witness is armed. Empty string when idle."""
    with _reg_lock:
        rows = []
        for ident, (tname, held) in sorted(_held_by_thread.items()):
            if not held:
                continue
            rows.append(f"  thread {tname!r} ({ident}):")
            for wit, site, _t, count in held:
                times = f" x{count}" if count > 1 else ""
                rows.append(f"    holds {wit.name!r}{times} "
                            f"acquired at {site}")
        if not rows:
            return ""
        return "witness-held locks per thread:\n" + "\n".join(rows)


def reset() -> None:
    """Drop all witness state (tests only — live witnesses keep working,
    their next acquisitions rebuild the graph)."""
    global _ctx
    with _reg_lock:
        _held_by_thread.clear()
        _succ.clear()
        _edge_sites.clear()
        _c401.clear()
    _ctx = None
