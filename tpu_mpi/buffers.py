"""Buffers: the array-type registry and send/recv operand normalization.

Reference: /root/reference/src/buffers.jl — MPIBuffertype union (:9), MPIPtr
conversion (:13-23), @assert_minlength bounds guard (:25-31), the
Buffer(data,count,datatype) triple (:78-91) with constructors for arrays, Refs
and three SubArray flavors that auto-derive vector/subarray datatypes
(:101-117), Buffer_send for isbits scalars (:125), and the CUDA extension
(src/cuda.jl:6-28) that plugs device arrays into the same conversion.

TPU mapping (SURVEY.md §2.2/§2.3): a buffer is either a host numpy array
(mutable, views welcome — numpy's strided views subsume the reference's
auto-derived SubArray datatypes) or a device-resident jax.Array. jax.Arrays are
immutable, so the mutating API accepts :class:`DeviceBuffer`, a thin rebinding
cell whose ``__setitem__`` lowers to functional ``.at[].set`` updates — the
pluggable array-registry pattern BASELINE.json asks for, with numpy and jax
registered by default.
"""

from __future__ import annotations

import weakref
from typing import Any, Optional

import numpy as np

from .datatypes import Datatype, to_datatype
from . import error as _ec
from .error import MPIError

# Host arrays created by to_wire as private snapshots — explicitly marked so
# in-place consumers (the multi-process ring allreduce) key their
# no-second-copy fast path on provenance, not on inferred numpy flags that a
# future caller's owning-but-shared array could also satisfy (ADVICE r2).
# Keyed by id with weakly-referenced values (ndarrays are weakref-able but
# not hashable): an entry dies with its array, so marking never extends a
# snapshot's lifetime and a recycled id can never alias a live entry.
_wire_snapshots: "weakref.WeakValueDictionary[int, np.ndarray]" = \
    weakref.WeakValueDictionary()


def _mark_wire_snapshot(arr: np.ndarray) -> np.ndarray:
    _wire_snapshots[id(arr)] = arr
    return arr


def is_wire_snapshot(arr: Any) -> bool:
    """True iff ``arr`` is a private host copy minted by :func:`to_wire`
    (safe to mutate in place: no user alias can exist)."""
    return _wire_snapshots.get(id(arr)) is arr


class _InPlace:
    """Sentinel for in-place collectives (src/collective.jl:1 IN_PLACE)."""

    def __repr__(self) -> str:
        return "IN_PLACE"


IN_PLACE = _InPlace()
BUFFER_NULL = None


def is_jax_array(x: Any) -> bool:
    return type(x).__module__.startswith("jax") and hasattr(x, "dtype")


class DeviceBuffer:
    """A mutable cell holding a device-resident jax.Array.

    The analog of passing a CuArray to MPI.jl (src/cuda.jl:26-28): device data
    is a first-class communication operand. Mutation rebinds via functional
    updates, so the mutating API (Recv!, Allreduce! with a recv buffer, …)
    works identically for host and device arrays.
    """

    def __init__(self, value: Any, dtype: Any = None, device: Any = None):
        import jax.numpy as jnp
        arr = jnp.asarray(value, dtype=dtype)
        if device is not None:
            import jax
            arr = jax.device_put(arr, device)
        self.value = arr

    # -- constructors mirroring ArrayType{T}(undef, dims) test usage ---------
    @classmethod
    def empty(cls, shape: Any, dtype: Any = np.float64) -> "DeviceBuffer":
        import jax.numpy as jnp
        return cls(jnp.zeros(shape, dtype=dtype))

    @property
    def shape(self):
        return self.value.shape

    @property
    def dtype(self):
        return self.value.dtype

    @property
    def size(self) -> int:
        return int(self.value.size)

    def __len__(self) -> int:
        return int(self.value.shape[0]) if self.value.ndim else 0

    def __array__(self, dtype=None):
        out = np.asarray(self.value)
        return out.astype(dtype) if dtype is not None else out

    def __getitem__(self, idx):
        return self.value[idx]

    def __setitem__(self, idx, val):
        self.value = self.value.at[idx].set(val)

    def setflat(self, src: Any, count: Optional[int] = None) -> None:
        """Assign the first ``count`` flat elements from src."""
        v = self.value
        # Fast path: full replacement by an identically-shaped jax array is a
        # pure rebind — no device dispatch at all. This is the hot lane of the
        # host-path collectives (the combined result is handed straight back).
        if (is_jax_array(src) and src.dtype == v.dtype and src.shape == v.shape
                and (count is None or count == v.size)):
            self.value = src
            return
        import jax.numpy as jnp
        n = (count if count is not None
             else int(np.prod(np.shape(src), dtype=np.int64)))
        if n == v.size and v.shape == tuple(np.shape(src)):
            self.value = jnp.asarray(src, dtype=v.dtype)
        else:
            flat = jnp.ravel(jnp.asarray(src, dtype=v.dtype))
            out = jnp.ravel(v).at[:n].set(flat[:n])
            self.value = out.reshape(v.shape)

    def copy(self) -> "DeviceBuffer":
        return DeviceBuffer(self.value)

    def fill(self, v: Any) -> None:
        import jax.numpy as jnp
        self.value = jnp.full(self.value.shape, v, dtype=self.value.dtype)

    def __repr__(self) -> str:
        return f"DeviceBuffer({self.value!r})"


class Buffer:
    """(data, count, datatype) communication operand (src/buffers.jl:78-91)."""

    def __init__(self, data: Any, count: Optional[int] = None,
                 datatype: Optional[Datatype] = None):
        self.data = data
        arr = extract_array(data)
        if arr is None:
            raise MPIError(f"not a communication buffer: {type(data).__name__}",
                           code=_ec.ERR_BUFFER)
        self.count = count if count is not None else int(arr.size)
        self.datatype = datatype if datatype is not None else to_datatype(arr.dtype)

    @property
    def array(self):
        return extract_array(self.data)


def Buffer_send(x: Any) -> Buffer:
    """Normalize any send operand, incl. scalars (src/buffers.jl:125)."""
    if isinstance(x, Buffer):
        return x
    if np.isscalar(x) or isinstance(x, (int, float, complex, bool, np.generic)):
        return Buffer(np.asarray(x))
    return Buffer(x)


def extract_array(x: Any):
    """The underlying numpy/jax array of an operand, or None.

    The array-type registry: numpy arrays (incl. non-contiguous views — strided
    views play the role of the reference's auto-derived SubArray datatypes,
    src/buffers.jl:101-117), jax.Arrays, DeviceBuffer cells, scalars, and
    nested sequences.
    """
    if isinstance(x, DeviceBuffer):
        return x.value
    if isinstance(x, np.ndarray) or is_jax_array(x):
        return x
    if isinstance(x, (np.generic, int, float, complex, bool)):
        return np.asarray(x)
    if isinstance(x, (list, tuple)) and x and not isinstance(x[0], (list, tuple)):
        return None  # plain sequences must be wrapped explicitly to avoid surprises
    return None


def element_count(x: Any) -> int:
    arr = extract_array(x)
    if arr is None:
        raise MPIError(f"not a communication buffer: {type(x).__name__}",
                       code=_ec.ERR_BUFFER)
    return int(arr.size)


def assert_minlength(buf: Any, count: int) -> None:
    """Bounds guard; raises AssertionError like the reference's
    @assert_minlength (src/buffers.jl:25-31)."""
    n = element_count(buf)
    assert n >= count, f"buffer has {n} elements, needs at least {count}"


def is_writable(x: Any) -> bool:
    if isinstance(x, DeviceBuffer):
        return True
    if isinstance(x, np.ndarray):
        return x.flags.writeable
    return False


def write_flat(dest: Any, src: Any, count: Optional[int] = None) -> Any:
    """Write the first ``count`` flat elements of src into dest.

    dest: numpy array (strided views fine) or DeviceBuffer. Returns dest.
    """
    if isinstance(dest, DeviceBuffer):
        dest.setflat(src, count)
        return dest
    if isinstance(dest, np.ndarray):
        srcarr = np.asarray(src)
        n = srcarr.size if count is None else count
        if n == dest.size and srcarr.size == dest.size:
            # strided-safe elementwise assignment
            dest[...] = srcarr.reshape(dest.shape).astype(dest.dtype, copy=False) \
                if srcarr.shape != dest.shape else srcarr.astype(dest.dtype, copy=False)
        elif dest.flags.c_contiguous:
            # contiguous: reshape(-1) is a VIEW, and direct slice assignment
            # is a memcpy — ndarray.flat's iterator assignment is ~8x slower
            # at MiB sizes, which dominates the RMA bulk path
            dest.reshape(-1)[:n] = srcarr.reshape(-1)[:n]
        else:
            # ndarray.flat is a logical C-order view regardless of the
            # underlying strides, so partial writes land at the right logical
            # positions even for reversed/transposed/F-ordered views.
            dest.flat[:n] = srcarr.reshape(-1)[:n]
        return dest
    if is_jax_array(dest):
        raise MPIError("jax.Array is immutable; wrap it in DeviceBuffer for "
                       "the mutating API, or use the allocating variant",
                       code=_ec.ERR_BUFFER)
    raise MPIError(f"cannot write into {type(dest).__name__}", code=_ec.ERR_BUFFER)


def write_range(buf: Any, off: int, new: np.ndarray) -> None:
    """Write 1-d ``new`` into the flat element range [off, off+len(new)) of a
    window-exposable buffer (the RMA write primitive: onesided.Put /
    Accumulate and the multi-process owner apply path share it). DeviceBuffer
    targets rebind the whole array; host arrays write in place."""
    n = int(np.asarray(new).size)
    if isinstance(buf, DeviceBuffer):
        flat = buf.value.reshape(-1).at[off:off + n].set(
            np.asarray(new, dtype=buf.value.dtype))
        buf.value = flat.reshape(buf.value.shape)
    else:
        arr = extract_array(buf)
        if arr is None:
            raise MPIError(f"cannot write into {type(buf).__name__}")
        tgt = np.asarray(arr)
        if tgt.flags.c_contiguous:
            # contiguous: reshape(-1) is a VIEW and slice assignment is a
            # memcpy; .flat's iterator assignment is ~8x slower at MiB sizes
            tgt.reshape(-1)[off:off + n] = new
        else:
            # .flat is a logical C-order view regardless of strides —
            # reshape(-1) on a non-contiguous view would copy and silently
            # drop the write
            tgt.flat[off:off + n] = new


def resolve_attached(attached, addr: int, who: str):
    """Resolve a dynamic-window byte address against an attach list of
    (base_addr, nbytes, buf) entries → (buf, array, element offset). Shared
    by the in-process and multi-process dynamic-window paths
    (src/onesided.jl:109-121 addressing contract)."""
    addr = int(addr)
    for (base_addr, nbytes, buf) in attached:
        if base_addr <= addr < base_addr + nbytes:
            arr = extract_array(buf)
            off = (addr - base_addr) // arr.dtype.itemsize
            return buf, arr, int(off)
    raise MPIError(f"address {addr:#x} not attached on rank {who}")


def clone_like(x: Any, value: Any) -> Any:
    """An operand of the same registry kind as x holding ``value``."""
    if isinstance(x, DeviceBuffer):
        return DeviceBuffer(value)
    if is_jax_array(x):
        import jax.numpy as jnp
        return jnp.asarray(value)
    return np.array(value, copy=True)


def to_wire(x: Any, count: Optional[int] = None) -> Any:
    """A contiguous, immutable-by-convention snapshot of a send operand.

    Host arrays are copied (the sender may mutate after a buffered Isend);
    device arrays are immutable so the reference is the snapshot — the zero-copy
    win of device-native buffers (SURVEY.md L5).

    With ``count``, host snapshots come back FLAT and OWNING (base None,
    owndata) in a single copy — downstream in-place consumers (the
    multi-process ring allreduce) key their no-second-copy fast path on
    those flags, and a flat view of a private copy would defeat it.
    """
    if isinstance(x, DeviceBuffer):
        arr = x.value
    elif is_jax_array(x):
        arr = x
    else:
        src = np.asarray(x)
        if count is None:
            arr = np.ascontiguousarray(src)
            return _mark_wire_snapshot(arr.copy() if arr is src else arr)
        out = np.ravel(src)           # view (contiguous) or owning copy
        if out.size != count:
            out = out[:count]
        if out.base is not None or out is src:
            out = out.copy()          # the single snapshot copy
        return _mark_wire_snapshot(out)
    if count is not None:
        shape = arr.shape
        if len(shape) == 1 and shape[0] == count:
            return arr
        flat = arr.reshape(-1)
        return flat if flat.size == count else flat[:count]
    return arr


def wire_view(x: Any, count: Optional[int] = None) -> Any:
    """A contiguous flat VIEW of a send operand — the zero-copy sibling of
    :func:`to_wire` for contributions whose rendezvous output is always a
    FRESH array (the reduce-family fold): every rank stays blocked in the
    rendezvous until the fold has run, so the live buffer cannot change
    under the combiner, and nothing downstream retains the view after the
    pick. Deliberately NOT marked as a wire snapshot — in-place consumers
    (the multi-process ring allreduce) must still copy before mutating.
    Falls back to :func:`to_wire` when a flat view can't be taken without a
    copy (non-contiguous host views), so callers always get wire shape."""
    if isinstance(x, DeviceBuffer) or is_jax_array(x):
        return to_wire(x, count)      # device refs are already zero-copy
    src = np.asarray(x)
    if not src.flags.c_contiguous:
        return to_wire(x, count)
    flat = src.reshape(-1)
    if count is not None and flat.size != count:
        flat = flat[:count]
    return flat


# Registered (pinned) host scratch arrays, minted by register_scratch() for
# the persistent-collective fast path (docs/performance.md "Registered
# buffers"): private to the runtime, never aliased by user data, so folds
# may mutate them in place round after round with zero steady-state
# allocation. Same id-keyed weak marking scheme as _wire_snapshots.
_registered: "weakref.WeakValueDictionary[int, np.ndarray]" = \
    weakref.WeakValueDictionary()


def register_scratch(count: int, dtype: Any) -> np.ndarray:
    """A pinned, runtime-private flat host array for a plan-bound fold
    accumulator. Registered buffers are allocated once at plan creation
    (``Allreduce_init``) and reused by every round — the zero-alloc
    contract the registered fast path is built on."""
    arr = np.empty(int(count), dtype=np.dtype(dtype))
    _registered[id(arr)] = arr
    return arr


def is_registered(arr: Any) -> bool:
    """True iff ``arr`` is a runtime-private registered scratch buffer
    (safe to fold into in place; no user alias can exist)."""
    return _registered.get(id(arr)) is arr


def pinned_wire_view(x: Any, count: int) -> Optional[np.ndarray]:
    """A STABLE flat view of a host send operand, bindable once at plan
    creation: later rounds reuse the view with no per-call normalization.
    Returns None when the operand cannot be pre-bound — non-ndarray kinds
    (DeviceBuffer rebinds its array every round; jax arrays are replaced,
    not mutated), non-contiguous views (wire_view would copy), or object
    dtype. The caller falls back to per-call :func:`wire_view`."""
    if not isinstance(x, np.ndarray) or x.dtype == object:
        return None
    if not x.flags.c_contiguous:
        return None
    flat = x.reshape(-1)
    return flat if flat.size == count else flat[:count]


_POISON_BYTE = 0xA5


def poison_fill(buf: Any, count: Optional[int] = None) -> None:
    """Fill the first ``count`` flat elements of an origin buffer with a loud
    sentinel (strict mode, docs/performance.md "Batched read epochs"): floats
    and complexes become NaN, ints the repeated-0xA5 bit pattern — so a
    caller consuming a deferred Get/Fetch_and_op origin before the closing
    synchronization sees obviously-poisoned values (NaN propagates;
    0xA5A5… is unmistakable) instead of plausible stale data. Object-dtype
    and other unpoisonable operands are left untouched."""
    arr = extract_array(buf)
    if arr is None:
        return
    n = int(arr.size if count is None else min(int(count), arr.size))
    if n <= 0:
        return
    dt = np.dtype(arr.dtype)
    if dt.kind == "f":
        val = dt.type(np.nan)
    elif dt.kind == "c":
        val = dt.type(complex(np.nan, np.nan))
    elif dt.kind in "iub":
        val = np.frombuffer(bytes([_POISON_BYTE]) * dt.itemsize, dtype=dt)[0]
    else:
        return
    if isinstance(buf, DeviceBuffer):
        write_range(buf, 0, np.full(n, val, dtype=dt))
    elif isinstance(buf, np.ndarray):
        if buf.flags.c_contiguous:
            buf.reshape(-1)[:n] = val
        else:
            buf.flat[:n] = val


# The reference's dispatch unions (src/buffers.jl:1-11) as isinstance()
# tuples. Deliberate divergences from the Julia unions: native Python
# scalars (int/float/complex/bool) are included — the typed send path
# accepts them — and numpy bools are in MPIDatatype (BOOL is a predefined
# datatype here) while Julia's Char has no scalar Python analog (1-char
# strings travel on the object path instead). Python-ism to know: bool
# subclasses int, so isinstance(True, MPIInteger) is True (Julia's Bool
# is not in its MPIInteger) — dispatch that must distinguish bools checks
# them BEFORE the integer union.
MPIInteger = (int, np.int8, np.uint8, np.int16, np.uint16,
              np.int32, np.uint32, np.int64, np.uint64)
MPIFloatingPoint = (float, np.float32, np.float64, np.float16)
MPIComplex = (complex, np.complex64, np.complex128)
MPIDatatype = (bool, np.bool_) + MPIInteger + MPIFloatingPoint + MPIComplex
