"""Data-parallel MLP: the minimum end-to-end slice (SURVEY.md §7 step 3).

Exercises launcher → mesh → collective → op → buffer: params broadcast from
rank 0 (Bcast analog: params enter replicated), per-shard forward/backward on
the MXU, one psum of gradients over the 'dp' axis (Allreduce analog), SGD.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..parallel.dp import allreduce_grads


def mlp_init(key, sizes: list[int]) -> list[dict[str, jnp.ndarray]]:
    params = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, sub = jax.random.split(key)
        params.append({
            "w": jax.random.normal(sub, (a, b), jnp.float32) * (2.0 / a) ** 0.5,
            "b": jnp.zeros((b,), jnp.float32),
        })
    return params


def _forward(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i != len(params) - 1:
            x = jax.nn.relu(x)
    return x


def mlp_train_step_dp(params: Any, x: jnp.ndarray, y: jnp.ndarray,
                      lr: float = 1e-2, axis: str = "dp"):
    """One SGD step on a batch shard; grads all-reduced over ``axis``.
    Call inside shard_map with x/y sharded over the batch dim."""

    def loss_fn(p):
        pred = _forward(p, x)
        return jnp.mean((pred - y) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    grads = allreduce_grads(grads, axis=axis, mean=True)
    new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
    from jax import lax
    return new_params, lax.pmean(loss, axis)
