"""Flagship model: a GPT-style transformer trained with DP × TP × SP.

Proves the whole substrate at once (SURVEY.md §2.5 / §5): batch sharded over
'dp' (gradient psum), attention heads + FFN hidden sharded over 'tp'
(Megatron column/row-parallel with the f/g operators from
tpu_mpi.parallel.tp), sequence sharded over 'sp' with exact ring attention
(ppermute ring from tpu_mpi.parallel.ring), RoPE positions offset per
sequence shard. Everything is one shard_map-wrapped, jitted, differentiable
train step — the TPU-native shape of a program the reference's users would
write with Allreduce!/Sendrecv!/Alltoall! by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.dp import allreduce_grads
from ..parallel.ring import ring_attention
from ..parallel.tp import column_parallel, row_parallel


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_seq: int = 512
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def transformer_init(key, cfg: TransformerConfig) -> dict:
    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    keys = jax.random.split(key, 2 + 4 * cfg.n_layers)
    d, f = cfg.d_model, cfg.d_ff
    params = {
        "embed": dense(keys[0], (cfg.vocab, d), d ** -0.5),
        "ln_f": jnp.ones((d,), cfg.dtype),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = keys[2 + 4 * i: 6 + 4 * i]
        params["layers"].append({
            "ln1": jnp.ones((d,), cfg.dtype),
            "w_qkv": dense(k[0], (d, 3 * d), d ** -0.5),
            "w_proj": dense(k[1], (d, d), (2 * d * cfg.n_layers) ** -0.5),
            "ln2": jnp.ones((d,), cfg.dtype),
            "w_in": dense(k[2], (d, f), d ** -0.5),
            "w_out": dense(k[3], (f, d), (2 * f * cfg.n_layers) ** -0.5),
        })
    return params


def transformer_param_specs(cfg: TransformerConfig, tp_axis: Optional[str]) -> dict:
    """PartitionSpec pytree matching transformer_init's params: qkv/ffn-in
    column-sharded, proj/ffn-out row-sharded over the tp axis; everything
    else replicated."""
    col = P(None, tp_axis)
    row = P(tp_axis, None)
    rep = P()
    return {
        "embed": rep,
        "ln_f": rep,
        "layers": [{
            "ln1": rep, "w_qkv": col, "w_proj": row,
            "ln2": rep, "w_in": col, "w_out": row,
        } for _ in range(cfg.n_layers)],
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _rope(x, positions):
    """Rotary embeddings; positions are *global* so sequence shards agree."""
    b, h, t, dh = x.shape
    half = dh // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]   # (t, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def transformer_forward(cfg: TransformerConfig, params: dict,
                        tokens: jnp.ndarray, *, tp_axis: Optional[str] = None,
                        sp_axis: Optional[str] = None) -> jnp.ndarray:
    """Logits for a (possibly dp/sp-sharded) local token block.

    tokens: (batch_local, seq_local) int32. Inside shard_map, ``tp_axis`` /
    ``sp_axis`` name live mesh axes; with both None this is a plain
    single-device forward (the driver's single-chip entry).
    """
    b, t = tokens.shape
    d, h = cfg.d_model, cfg.n_heads
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    if h % tp != 0:
        raise ValueError(f"n_heads={h} must be divisible by tp size {tp}")
    h_local = h // tp
    dh = cfg.head_dim

    # global positions for this sequence shard (RoPE must see them)
    if sp_axis is not None:
        sp_idx = lax.axis_index(sp_axis)
        positions = sp_idx * t + jnp.arange(t)
    else:
        positions = jnp.arange(t)

    x = params["embed"][tokens]                                   # (b, t, d)
    for layer in params["layers"]:
        x = _attn_ffn_block(cfg, layer, x, positions,
                            tp_axis=tp_axis, sp_axis=sp_axis)
    x = _rms_norm(x, params["ln_f"])
    return (x @ params["embed"].T).astype(jnp.float32)            # (b, t, V)


def _attn_ffn_block(cfg: TransformerConfig, layer: dict, x: jnp.ndarray,
                    positions: jnp.ndarray, *, tp_axis: Optional[str],
                    sp_axis: Optional[str]) -> jnp.ndarray:
    """One transformer layer (pre-norm attention + FFN), tp/sp aware —
    shared by the flat forward and the pipelined 4-axis stage."""
    b, t, _ = x.shape
    h = cfg.n_heads
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    h_local = h // tp
    dh = cfg.head_dim

    # -- attention --
    y = _rms_norm(x, layer["ln1"])
    if tp_axis is not None:
        qkv = column_parallel(y, layer["w_qkv"], axis=tp_axis)
    else:
        qkv = y @ layer["w_qkv"]                              # (b, t, 3d/tp)
    # w_qkv columns are packed per head ([head][q|k|v][dh]) so a
    # contiguous tp column shard holds whole heads and the sharded
    # forward equals the single-device one.
    qkv = qkv.reshape(b, t, h_local, 3, dh).transpose(0, 2, 1, 3, 4)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    q = _rope(q, positions)
    k = _rope(k, positions)
    if sp_axis is not None:
        o = ring_attention(q, k, v, axis=sp_axis, causal=True)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", q * dh ** -0.5, k)
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask, s, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
    o = o.transpose(0, 2, 1, 3).reshape(b, t, h_local * dh)
    if tp_axis is not None:
        x = x + row_parallel(o, layer["w_proj"], axis=tp_axis)
    else:
        x = x + o @ layer["w_proj"]

    # -- feed-forward --
    y = _rms_norm(x, layer["ln2"])
    if tp_axis is not None:
        hmid = jax.nn.gelu(column_parallel(y, layer["w_in"], axis=tp_axis))
        x = x + row_parallel(hmid, layer["w_out"], axis=tp_axis)
    else:
        x = x + jax.nn.gelu(y @ layer["w_in"]) @ layer["w_out"]
    return x


def _xent(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def transformer_train_step(cfg: TransformerConfig, mesh, lr: float = 1e-2, *,
                           dp_axis: str = "dp", tp_axis: str = "tp",
                           sp_axis: str = "sp"):
    """Build the jitted DP×TP×SP train step over ``mesh``.

    Returns (step, param_specs): ``step(params, tokens, labels) -> (params,
    loss)`` where tokens/labels are global (batch, seq) arrays sharded
    (batch→dp, seq→sp) by shard_map, and params follow param_specs.
    """
    specs = transformer_param_specs(cfg, tp_axis)
    axis_names = set(mesh.axis_names)
    for a in (dp_axis, tp_axis, sp_axis):
        if a not in axis_names:
            raise ValueError(f"mesh is missing axis {a!r}")
    reduce_axes = (dp_axis, sp_axis)

    def local_step(params, tokens, labels):
        def loss_fn(p):
            logits = transformer_forward(cfg, p, tokens, tp_axis=tp_axis,
                                         sp_axis=sp_axis)
            return _xent(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dp/sp shards saw different tokens: sum their param grads. The tp
        # direction needs no reduction — the f/g operators already produced
        # tp-correct grads (sharded params local, replicated params invariant).
        grads = jax.tree_util.tree_map(lambda g: lax.psum(g, reduce_axes), grads)
        params = jax.tree_util.tree_map(lambda p, g: (p - lr * g).astype(p.dtype),
                                        params, grads)
        loss = lax.pmean(loss, reduce_axes)
        return params, loss

    data_spec = P(dp_axis, sp_axis)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P())))
    return step, specs


# ---------------------------------------------------------------------------
# pipeline x expert-parallel variant: the remaining two axes of the 5-way
# parallelism matrix (SURVEY.md §2.5 rows PP and EP), composed in one step
# ---------------------------------------------------------------------------

def transformer_pp_moe_init(key, cfg: TransformerConfig, n_experts: int) -> dict:
    """Layer-stacked params for the pipelined MoE transformer: every layer
    tensor carries a leading (n_layers,) dim (sharded over 'pp'); the expert
    FFN weights add an (n_experts,) dim (sharded over 'ep')."""
    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    d, f, L, E = cfg.d_model, cfg.d_ff, cfg.n_layers, n_experts
    keys = jax.random.split(key, 6)
    return {
        "embed": dense(keys[0], (cfg.vocab, d), d ** -0.5),
        "ln_f": jnp.ones((d,), cfg.dtype),
        "ln1": jnp.ones((L, d), cfg.dtype),
        "w_qkv": dense(keys[1], (L, d, 3 * d), d ** -0.5),
        "w_proj": dense(keys[2], (L, d, d), (2 * d * L) ** -0.5),
        "ln2": jnp.ones((L, d), cfg.dtype),
        "w_gate": dense(keys[3], (L, d, E), d ** -0.5),
        "w_in": dense(keys[4], (L, E, d, f), d ** -0.5),
        "w_out": dense(keys[5], (L, E, f, d), (2 * f * L) ** -0.5),
    }


def transformer_pp_moe_specs(pp_axis: str, ep_axis: str) -> dict:
    """PartitionSpecs matching transformer_pp_moe_init."""
    lyr = P(pp_axis)
    return {
        "embed": P(), "ln_f": P(),
        "ln1": lyr, "w_qkv": lyr, "w_proj": lyr, "ln2": lyr,
        "w_gate": lyr,
        "w_in": P(pp_axis, ep_axis), "w_out": P(pp_axis, ep_axis),
    }


def _pp_moe_stage(cfg: TransformerConfig, n_experts: int, ep_axis: str,
                  capacity: int, stage_params: dict, x: jnp.ndarray,
                  positions: jnp.ndarray) -> jnp.ndarray:
    """One pipeline stage: this rank's block of layers, each a causal dense
    attention plus a top-1 MoE FFN routed over the 'ep' axis."""
    from ..parallel.ep import moe_dispatch_combine

    b, t, d = x.shape
    h, dh = cfg.n_heads, cfg.head_dim
    L_local = stage_params["w_qkv"].shape[0]
    for i in range(L_local):
        # -- attention (heads local: this config spends its devices on pp/ep)
        y = _rms_norm(x, stage_params["ln1"][i])
        qkv = (y @ stage_params["w_qkv"][i]).reshape(b, t, h, 3, dh)
        qkv = qkv.transpose(0, 2, 1, 3, 4)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        q, k = _rope(q, positions), _rope(k, positions)
        s = jnp.einsum("bhqd,bhkd->bhqk", q * dh ** -0.5, k)
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask, s, -1e30)
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, d)
        x = x + o @ stage_params["w_proj"][i]

        # -- MoE FFN: route each token to its argmax expert over 'ep';
        # Switch-style scaling by the selected gate probability keeps the
        # router differentiable (argmax alone would never train w_gate)
        y = _rms_norm(x, stage_params["ln2"][i]).reshape(b * t, d)
        gate = jax.nn.softmax(y @ stage_params["w_gate"][i], axis=-1)
        eidx = jnp.argmax(gate, axis=-1)
        p_sel = jnp.take_along_axis(gate, eidx[:, None], axis=-1)
        w_in = stage_params["w_in"][i, 0]      # this rank's expert shard
        w_out = stage_params["w_out"][i, 0]

        def expert(tok):
            return jax.nn.gelu(tok @ w_in) @ w_out

        out = moe_dispatch_combine(y, eidx.astype(jnp.int32), expert,
                                   capacity=capacity, axis=ep_axis)
        x = x + (out * p_sel).reshape(b, t, d)
    return x


def transformer_pp_moe_host_params(params: dict, cfg: TransformerConfig,
                                   n_experts: int, stage: int,
                                   n_stages: int, expert: int) -> dict:
    """Numpy slice of one (pipeline stage, expert) shard of
    :func:`transformer_pp_moe_init` params, for the host-path inference
    engine (``tpu_mpi.infer``): the stage's slab of layer tensors plus
    ONLY this rank's expert FFN weights (w_in/w_out lose their expert
    dim). ``embed``/``ln_f`` ride along on every rank — stage 0 embeds,
    the last stage computes logits."""
    import numpy as np
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} must divide over "
                         f"{n_stages} pipeline stages")
    if not (0 <= expert < n_experts):
        raise ValueError(f"expert {expert} out of range [0, {n_experts})")
    per = cfg.n_layers // n_stages
    lo, hi = stage * per, (stage + 1) * per

    def host(a):
        return np.ascontiguousarray(np.asarray(a, dtype=np.float32))

    return {
        "embed": host(params["embed"]),
        "ln_f": host(params["ln_f"]),
        "ln1": host(params["ln1"][lo:hi]),
        "w_qkv": host(params["w_qkv"][lo:hi]),
        "w_proj": host(params["w_proj"][lo:hi]),
        "ln2": host(params["ln2"][lo:hi]),
        "w_gate": host(params["w_gate"][lo:hi]),
        "w_in": host(params["w_in"][lo:hi, expert]),
        "w_out": host(params["w_out"][lo:hi, expert]),
    }


def transformer_pp_moe_train_step(cfg: TransformerConfig, mesh,
                                  n_experts: int, lr: float = 1e-2, *,
                                  dp_axis: str = "dp", pp_axis: str = "pp",
                                  ep_axis: str = "ep",
                                  microbatches: Optional[int] = None):
    """Jitted DP × PP × EP train step: batch sharded over 'dp', layers
    sharded over 'pp' (GPipe microbatch rotation via
    tpu_mpi.parallel.pp.pipeline_forward), expert FFNs sharded over 'ep'
    (padded-all_to_all routing via tpu_mpi.parallel.ep). Together with
    transformer_train_step (DP × TP × SP) this covers the full 5-axis
    parallelism matrix of SURVEY.md §2.5.

    Returns (step, param_specs); step(params, tokens, labels) -> (params,
    loss). n_experts must equal the 'ep' axis size (one expert per rank).
    """
    from ..parallel.pp import pipeline_forward

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for a in (dp_axis, pp_axis, ep_axis):
        if a not in sizes:
            raise ValueError(f"mesh is missing axis {a!r}")
    if n_experts != sizes[ep_axis]:
        raise ValueError(f"n_experts={n_experts} must equal the {ep_axis!r} "
                         f"axis size {sizes[ep_axis]}")
    if cfg.n_layers % sizes[pp_axis]:
        raise ValueError(f"n_layers={cfg.n_layers} must divide over "
                         f"{sizes[pp_axis]} pipeline stages")
    n_pp = sizes[pp_axis]
    m = microbatches or max(2, 2 * n_pp)
    specs = transformer_pp_moe_specs(pp_axis, ep_axis)

    def local_step(params, tokens, labels):
        b, t = tokens.shape
        if b % m:
            raise ValueError(f"local batch {b} must divide into {m} microbatches")
        positions = jnp.arange(t)
        capacity = max(1, 2 * (b // m) * t // n_experts)

        def loss_fn(p):
            stage = {k: p[k] for k in
                     ("ln1", "w_qkv", "w_proj", "ln2", "w_gate",
                      "w_in", "w_out")}
            e = p["embed"][tokens].reshape(m, b // m, t, cfg.d_model)

            def stage_fn(sp_, x):
                return _pp_moe_stage(cfg, n_experts, ep_axis,
                                     capacity, sp_, x, positions)

            acts = pipeline_forward(stage_fn, stage, e, axis=pp_axis)
            acts = acts.reshape(b, t, cfg.d_model)
            logits = (_rms_norm(acts, p["ln_f"])
                      @ p["embed"].T).astype(jnp.float32)
            l = _xent(logits, labels)
            # only the last stage's emissions are the real model output
            last = lax.axis_index(pp_axis) == n_pp - 1
            return lax.psum(jnp.where(last, l, 0.0), pp_axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        def reduce_leaf(path_key, g):
            if path_key in ("w_in", "w_out"):
                # ep-sharded experts: each rank owns its expert's grads, but
                # the batch is REPLICATED over ep — every replica's loss
                # back-propagates through the same expert via the all_to_all
                # transpose, so the raw grad is ep_size times the per-batch
                # gradient; normalize or experts train at an inflated lr
                return lax.psum(g, dp_axis) / sizes[ep_axis]
            if path_key in ("embed", "ln_f"):
                # fully replicated, with distinct per-stage contributions
                return lax.pmean(lax.psum(g, (dp_axis, pp_axis)), ep_axis)
            # pp-sharded, ep-replicated layer tensors
            return lax.pmean(lax.psum(g, dp_axis), ep_axis)

        grads = {k: reduce_leaf(k, g) for k, g in grads.items()}
        params = jax.tree_util.tree_map(
            lambda p_, g: (p_ - lr * g).astype(p_.dtype), params, grads)
        loss = lax.pmean(lax.pmean(loss, dp_axis), ep_axis)
        return params, loss

    data_spec = P(dp_axis, None)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P())))
    return step, specs


# ---------------------------------------------------------------------------
# 4-axis variant: DP x TP x SP x PP in ONE step (VERDICT r3 #9). Layers are
# stacked over 'pp' (GPipe microbatch rotation), attention/FFN weights are
# Megatron-sharded over 'tp', the sequence is ring-attention-sharded over
# 'sp', and the batch over 'dp' — four simultaneously nontrivial axes.
# ---------------------------------------------------------------------------

def transformer_4d_init(key, cfg: TransformerConfig) -> dict:
    """Layer-stacked dense params: every layer tensor carries a leading
    (n_layers,) dim (sharded over 'pp'); within a layer the shapes match
    transformer_init's per-layer dicts."""
    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    keys = jax.random.split(key, 5)
    return {
        "embed": dense(keys[0], (cfg.vocab, d), d ** -0.5),
        "ln_f": jnp.ones((d,), cfg.dtype),
        "ln1": jnp.ones((L, d), cfg.dtype),
        "w_qkv": dense(keys[1], (L, d, 3 * d), d ** -0.5),
        "w_proj": dense(keys[2], (L, d, d), (2 * d * L) ** -0.5),
        "ln2": jnp.ones((L, d), cfg.dtype),
        "w_in": dense(keys[3], (L, d, f), d ** -0.5),
        "w_out": dense(keys[4], (L, f, d), (2 * f * L) ** -0.5),
    }


def transformer_4d_specs(pp_axis: str, tp_axis: str) -> dict:
    """PartitionSpecs matching transformer_4d_init: leading layer dim over
    pp; Megatron column/row sharding over tp within each layer."""
    return {
        "embed": P(), "ln_f": P(),
        "ln1": P(pp_axis), "ln2": P(pp_axis),
        "w_qkv": P(pp_axis, None, tp_axis),    # column-parallel
        "w_proj": P(pp_axis, tp_axis, None),   # row-parallel
        "w_in": P(pp_axis, None, tp_axis),
        "w_out": P(pp_axis, tp_axis, None),
    }


def transformer_4d_train_step(cfg: TransformerConfig, mesh, lr: float = 1e-2,
                              *, dp_axis: str = "dp", tp_axis: str = "tp",
                              sp_axis: str = "sp", pp_axis: str = "pp",
                              microbatches: Optional[int] = None):
    """Jitted DP x TP x SP x PP train step (the flagship on a 4-axis mesh):
    batch over dp, Megatron f/g matmuls over tp, ring attention over sp,
    GPipe stages over pp. Returns (step, param_specs); step(params, tokens,
    labels) -> (params, loss) with tokens/labels global (batch, seq) arrays
    sharded (batch->dp, seq->sp)."""
    from ..parallel.pp import pipeline_forward

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    for a in (dp_axis, tp_axis, sp_axis, pp_axis):
        if a not in sizes:
            raise ValueError(f"mesh is missing axis {a!r}")
    if cfg.n_heads % sizes[tp_axis]:
        raise ValueError(f"n_heads={cfg.n_heads} must divide over tp size "
                         f"{sizes[tp_axis]}")
    if cfg.n_layers % sizes[pp_axis]:
        raise ValueError(f"n_layers={cfg.n_layers} must divide over "
                         f"{sizes[pp_axis]} pipeline stages")
    n_pp = sizes[pp_axis]
    m = microbatches or max(2, 2 * n_pp)
    specs = transformer_4d_specs(pp_axis, tp_axis)

    def local_step(params, tokens, labels):
        b, t = tokens.shape            # local (dp- and sp-sharded) block
        if b % m:
            raise ValueError(f"local batch {b} must divide into {m} "
                             f"microbatches")
        sp_idx = lax.axis_index(sp_axis)
        positions = sp_idx * t + jnp.arange(t)

        def loss_fn(p):
            stage = {k: p[k] for k in ("ln1", "w_qkv", "w_proj", "ln2",
                                       "w_in", "w_out")}
            e = p["embed"][tokens].reshape(m, b // m, t, cfg.d_model)

            def stage_fn(sp_, x):
                for i in range(sp_["w_qkv"].shape[0]):     # local layers
                    layer = {k: v[i] for k, v in sp_.items()}
                    x = _attn_ffn_block(cfg, layer, x, positions,
                                        tp_axis=tp_axis, sp_axis=sp_axis)
                return x

            acts = pipeline_forward(stage_fn, stage, e, axis=pp_axis)
            acts = acts.reshape(b, t, cfg.d_model)
            logits = (_rms_norm(acts, p["ln_f"])
                      @ p["embed"].T).astype(jnp.float32)
            l = _xent(logits, labels)
            # only the last stage's emissions are the real model output
            last = lax.axis_index(pp_axis) == n_pp - 1
            return lax.psum(jnp.where(last, l, 0.0), pp_axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)

        def reduce_leaf(path_key, g):
            if path_key in ("embed", "ln_f"):
                # replicated everywhere; distinct contributions from each
                # dp/sp data shard and each pp stage (embed: the injected
                # activations on stage 0 + the logit matmul on the last)
                return lax.psum(g, (dp_axis, sp_axis, pp_axis))
            # pp-sharded layer stacks: dp/sp data shards sum; tp grads are
            # already correct from the f/g custom_vjp pair
            return lax.psum(g, (dp_axis, sp_axis))

        grads = {k: reduce_leaf(k, g) for k, g in grads.items()}
        params = jax.tree_util.tree_map(
            lambda p_, g: (p_ - lr * g).astype(p_.dtype), params, grads)
        loss = lax.pmean(loss, (dp_axis, sp_axis))
        return params, loss

    data_spec = P(dp_axis, sp_axis)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P())))
    return step, specs
