"""Flagship model: a GPT-style transformer trained with DP × TP × SP.

Proves the whole substrate at once (SURVEY.md §2.5 / §5): batch sharded over
'dp' (gradient psum), attention heads + FFN hidden sharded over 'tp'
(Megatron column/row-parallel with the f/g operators from
tpu_mpi.parallel.tp), sequence sharded over 'sp' with exact ring attention
(ppermute ring from tpu_mpi.parallel.ring), RoPE positions offset per
sequence shard. Everything is one shard_map-wrapped, jitted, differentiable
train step — the TPU-native shape of a program the reference's users would
write with Allreduce!/Sendrecv!/Alltoall! by hand.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.dp import allreduce_grads
from ..parallel.ring import ring_attention
from ..parallel.tp import column_parallel, row_parallel


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_seq: int = 512
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def transformer_init(key, cfg: TransformerConfig) -> dict:
    def dense(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    keys = jax.random.split(key, 2 + 4 * cfg.n_layers)
    d, f = cfg.d_model, cfg.d_ff
    params = {
        "embed": dense(keys[0], (cfg.vocab, d), d ** -0.5),
        "ln_f": jnp.ones((d,), cfg.dtype),
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = keys[2 + 4 * i: 6 + 4 * i]
        params["layers"].append({
            "ln1": jnp.ones((d,), cfg.dtype),
            "w_qkv": dense(k[0], (d, 3 * d), d ** -0.5),
            "w_proj": dense(k[1], (d, d), (2 * d * cfg.n_layers) ** -0.5),
            "ln2": jnp.ones((d,), cfg.dtype),
            "w_in": dense(k[2], (d, f), d ** -0.5),
            "w_out": dense(k[3], (f, d), (2 * f * cfg.n_layers) ** -0.5),
        })
    return params


def transformer_param_specs(cfg: TransformerConfig, tp_axis: Optional[str]) -> dict:
    """PartitionSpec pytree matching transformer_init's params: qkv/ffn-in
    column-sharded, proj/ffn-out row-sharded over the tp axis; everything
    else replicated."""
    col = P(None, tp_axis)
    row = P(tp_axis, None)
    rep = P()
    return {
        "embed": rep,
        "ln_f": rep,
        "layers": [{
            "ln1": rep, "w_qkv": col, "w_proj": row,
            "ln2": rep, "w_in": col, "w_out": row,
        } for _ in range(cfg.n_layers)],
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _rope(x, positions):
    """Rotary embeddings; positions are *global* so sequence shards agree."""
    b, h, t, dh = x.shape
    half = dh // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    ang = positions[:, None].astype(jnp.float32) * freqs[None, :]   # (t, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def transformer_forward(cfg: TransformerConfig, params: dict,
                        tokens: jnp.ndarray, *, tp_axis: Optional[str] = None,
                        sp_axis: Optional[str] = None) -> jnp.ndarray:
    """Logits for a (possibly dp/sp-sharded) local token block.

    tokens: (batch_local, seq_local) int32. Inside shard_map, ``tp_axis`` /
    ``sp_axis`` name live mesh axes; with both None this is a plain
    single-device forward (the driver's single-chip entry).
    """
    b, t = tokens.shape
    d, h = cfg.d_model, cfg.n_heads
    tp = 1 if tp_axis is None else lax.axis_size(tp_axis)
    if h % tp != 0:
        raise ValueError(f"n_heads={h} must be divisible by tp size {tp}")
    h_local = h // tp
    dh = cfg.head_dim

    # global positions for this sequence shard (RoPE must see them)
    if sp_axis is not None:
        sp_idx = lax.axis_index(sp_axis)
        positions = sp_idx * t + jnp.arange(t)
    else:
        positions = jnp.arange(t)

    x = params["embed"][tokens]                                   # (b, t, d)
    for layer in params["layers"]:
        # -- attention --
        y = _rms_norm(x, layer["ln1"])
        if tp_axis is not None:
            qkv = column_parallel(y, layer["w_qkv"], axis=tp_axis)
        else:
            qkv = y @ layer["w_qkv"]                          # (b, t, 3d/tp)
        # w_qkv columns are packed per head ([head][q|k|v][dh]) so a
        # contiguous tp column shard holds whole heads and the sharded
        # forward equals the single-device one.
        qkv = qkv.reshape(b, t, h_local, 3, dh).transpose(0, 2, 1, 3, 4)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        q = _rope(q, positions)
        k = _rope(k, positions)
        if sp_axis is not None:
            o = ring_attention(q, k, v, axis=sp_axis, causal=True)
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q * dh ** -0.5, k)
            mask = jnp.tril(jnp.ones((t, t), dtype=bool))
            s = jnp.where(mask, s, -1e30)
            o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        o = o.transpose(0, 2, 1, 3).reshape(b, t, h_local * dh)
        if tp_axis is not None:
            x = x + row_parallel(o, layer["w_proj"], axis=tp_axis)
        else:
            x = x + o @ layer["w_proj"]

        # -- feed-forward --
        y = _rms_norm(x, layer["ln2"])
        if tp_axis is not None:
            hmid = jax.nn.gelu(column_parallel(y, layer["w_in"], axis=tp_axis))
            x = x + row_parallel(hmid, layer["w_out"], axis=tp_axis)
        else:
            x = x + jax.nn.gelu(y @ layer["w_in"]) @ layer["w_out"]

    x = _rms_norm(x, params["ln_f"])
    return (x @ params["embed"].T).astype(jnp.float32)            # (b, t, V)


def _xent(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def transformer_train_step(cfg: TransformerConfig, mesh, lr: float = 1e-2, *,
                           dp_axis: str = "dp", tp_axis: str = "tp",
                           sp_axis: str = "sp"):
    """Build the jitted DP×TP×SP train step over ``mesh``.

    Returns (step, param_specs): ``step(params, tokens, labels) -> (params,
    loss)`` where tokens/labels are global (batch, seq) arrays sharded
    (batch→dp, seq→sp) by shard_map, and params follow param_specs.
    """
    specs = transformer_param_specs(cfg, tp_axis)
    axis_names = set(mesh.axis_names)
    for a in (dp_axis, tp_axis, sp_axis):
        if a not in axis_names:
            raise ValueError(f"mesh is missing axis {a!r}")
    reduce_axes = (dp_axis, sp_axis)

    def local_step(params, tokens, labels):
        def loss_fn(p):
            logits = transformer_forward(cfg, p, tokens, tp_axis=tp_axis,
                                         sp_axis=sp_axis)
            return _xent(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dp/sp shards saw different tokens: sum their param grads. The tp
        # direction needs no reduction — the f/g operators already produced
        # tp-correct grads (sharded params local, replicated params invariant).
        grads = jax.tree_util.tree_map(lambda g: lax.psum(g, reduce_axes), grads)
        params = jax.tree_util.tree_map(lambda p, g: (p - lr * g).astype(p.dtype),
                                        params, grads)
        loss = lax.pmean(loss, reduce_axes)
        return params, loss

    data_spec = P(dp_axis, sp_axis)
    step = jax.jit(jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P())))
    return step, specs
