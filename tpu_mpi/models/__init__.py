"""Demonstration models proving the communication substrate end-to-end.

The reference ships no models (it is a communication library); SURVEY.md §7's
build plan nonetheless requires "one model e2e" — a data-parallel step built
on rank/size + Bcast + Allreduce + Barrier — and §5 asks for a
ring-attention-shaped demo of the long-context substrate. These models are
that proof, written on the primitive layer (tpu_mpi.xla + tpu_mpi.parallel).
"""

from .mlp import mlp_init, mlp_train_step_dp
from .transformer import (TransformerConfig, transformer_forward,
                          transformer_init, transformer_train_step)
