"""tpu_mpi: a TPU-native message-passing framework.

The capability surface of MPI.jl (/root/reference/src/MPI.jl — environment,
communicators, point-to-point, collectives, reduction operators, derived
datatypes, Cartesian topology, one-sided RMA, parallel I/O, launcher),
re-designed for TPU: ranks are threads of one controller process bound to
devices; the semantic path runs over a host rendezvous engine with zero-copy
shared-memory placement; the performance path (``tpu_mpi.xla``) lowers the
same collectives to XLA ICI ops (psum / all_gather / all_to_all / ppermute)
inside jit/shard_map over a jax.sharding.Mesh.
"""

from .version import __version__

from . import _jax_compat  # installs jax.shard_map on older jax; keep first
from . import implementations
from .implementations import Get_library_version, Get_version

# Wildcards / sentinels
from ._runtime import (ANY_SOURCE, ANY_TAG, PROC_NULL, UNDEFINED,
                       SpmdContext, spmd_run)
from .error import (AbortError, AnalyzerError, CollectiveMismatchError,
                    DeadlockError, Error_string, Get_error_string,
                    InvalidCommError, LockOrderError, MPIError,
                    ProcFailedError, QuotaExceededError, RevokedError,
                    ServeBusyError, SessionError, TruncationError)

# Communication-correctness analysis (docs/analysis.md): static lint,
# cross-rank trace verifier, RMA race detector.
from . import analyze
from .analyze import Diagnostic

# Environment / lifecycle (src/environment.jl)
from .environment import (Abort, Finalize, Finalized, Init, Init_thread,
                          Initialized, Is_thread_main, Pcontrol, Query_thread,
                          THREAD_FUNNELED, THREAD_MULTIPLE, THREAD_SERIALIZED,
                          THREAD_SINGLE, ThreadLevel, Wtick, Wtime, has_tpu,
                          profile_trace, universe_size)

# Communicators (src/comm.jl)
from .comm import (COMM_NULL, COMM_SELF, COMM_TYPE_SHARED, COMM_WORLD,
                   CONGRUENT, Comm, Comm_agree, Comm_compare, Comm_dup,
                   Comm_get_parent, Comm_rank, Comm_revoke, Comm_shrink,
                   Comm_size, Comm_spawn, Comm_split, Comm_split_type,
                   Comparison, IDENT, Intercomm, Intercomm_merge, ROOT,
                   SIMILAR, UNEQUAL, free, spawn_argv)

# Object model
from .info import INFO_NULL, Info, infoval
from .buffers import (BUFFER_NULL, Buffer, Buffer_send, DeviceBuffer, IN_PLACE,
                      MPIComplex, MPIDatatype, MPIFloatingPoint, MPIInteger,
                      assert_minlength)
from .datatypes import (BFLOAT16, BOOL, BYTE, CHAR, COMPLEX64, COMPLEX128,
                        Datatype, FLOAT16, FLOAT32, FLOAT64, Get_address,
                        INT8, INT16, INT32, INT64, Types, UINT8, UINT16,
                        UINT32, UINT64, to_datatype)
from .operators import (BAND, BOR, BXOR, LAND, LOR, LXOR, MAX, MIN, NO_OP, Op,
                        PROD, REPLACE, SUM)

# Collectives (src/collective.jl) + nonblocking variants (MPI-3; absent
# from the reference — beyond parity) + persistent collectives (MPI-4)
from .collective import (Allgather, Allgatherv, Allreduce, Allreduce_init,
                         Alltoall, Alltoallv, Barrier, Barrier_init, Bcast,
                         Bcast_init, CollRequest, Exscan, Gather, Gatherv,
                         Iallgather, Iallreduce, Ialltoall, Ibarrier, Ibcast,
                         Iexscan, Igather, Ireduce, Iscan, Iscatter, Reduce,
                         Reduce_scatter, Reduce_scatter_block, Scan, Scatter,
                         Scatterv, bcast)
from .overlap import PersistentCollRequest
from . import overlap

# Point-to-point (src/pointtopoint.jl)
from .pointtopoint import (Cancel, Get_count, Get_error, Get_source, Get_tag,
                           Iprobe, Irecv, Isend, Isendrecv, Isendrecv_replace,
                           Parrived, PartitionedRequest, Pready, Pready_range,
                           Precv_init, Prequest, Probe, Psend_init, Recv,
                           Recv_init, Request, REQUEST_NULL, Send, Send_init,
                           Sendrecv, Sendrecv_replace, Start, Startall,
                           Status, STATUS_EMPTY, Test, Testall, Testany,
                           Testsome, Wait, Waitall, Waitany, Waitsome, irecv,
                           isend, recv, send)

# Parallel I/O (src/io.jl) — usage: MPI.File.open / read_at / write_at_all …
from . import io as File
from .io import FileHandle
# Sharded checkpoint/resume on top of the File layer (SURVEY.md §5)
from . import checkpoint

# One-sided RMA (src/onesided.jl)
from .onesided import (Accumulate, Fetch_and_op, Get, Get_accumulate,
                       LOCK_EXCLUSIVE, LOCK_SHARED, LockType, Put, Win,
                       Win_allocate_shared, Win_attach, Win_create,
                       Win_create_dynamic, Win_detach, Win_fence, Win_flush,
                       Win_lock, Win_shared_query, Win_sync, Win_unlock)

# Topology (src/topology.jl) + MPI-3 neighborhood collectives (absent from
# the reference — beyond parity)
from .topology import (Cart_coords, Cart_create, Cart_get, Cart_rank,
                       Cart_shift, Cart_sub, CartComm, Cartdim_get,
                       Dims_create, Neighbor_allgather, Neighbor_alltoall)
# Null-handle constants and library identity (reference parity:
# src/handle.jl null consts, src/implementations.jl MPI_LIBRARY /
# MPI_VERSION). No FFI handles exist here; each null is its own distinct
# sentinel so `x is MPI.WIN_NULL` cannot be confused with another handle
# kind or with a plain None default.


class _NullHandle:
    __slots__ = ("_name",)

    def __init__(self, name):
        self._name = name

    def __repr__(self):
        return self._name

    def __bool__(self):
        return False


DATATYPE_NULL = _NullHandle("DATATYPE_NULL")
OP_NULL = _NullHandle("OP_NULL")
WIN_NULL = _NullHandle("WIN_NULL")
FILE_NULL = _NullHandle("FILE_NULL")
MPI_LIBRARY = "tpu_mpi"
MPI_VERSION = Get_version()


def __getattr__(name):
    # lazily computed: building the version string imports jax
    if name == "MPI_LIBRARY_VERSION_STRING":
        return Get_library_version()
    if name == "serve":
        # lazy: the serve tier (broker + client sessions, docs/serving.md)
        # is only paid for by processes that use it
        import importlib
        return importlib.import_module(".serve", __name__)
    if name == "train":
        # lazy like serve: the training tier (docs/training.md) is only
        # paid for by processes that train
        import importlib
        return importlib.import_module(".train", __name__)
    raise AttributeError(f"module 'tpu_mpi' has no attribute {name!r}")


def install_tpurun(*args, **kwargs):
    """Install the ``tpurun`` wrapper executable (MPI.install_mpiexecjl
    analog). Lazy import: eagerly importing .launcher here would put it in
    sys.modules and make ``python -m tpu_mpi.launcher`` warn + re-execute."""
    from .launcher import install_tpurun as _install
    return _install(*args, **kwargs)
