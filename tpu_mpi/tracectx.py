"""Request-scoped trace context: the distributed-tracing spine of the serve
tier (docs/observability.md "Request traces").

A :class:`TraceCtx` is three fields — a 64-bit ``trace_id``, the parent
``span_id``, and the sampling bit — minted in ``serve/session.py`` when a
sampled op starts, carried across every hop in the frame metadata
(``meta["trace"] = {"id", "span", "s"}``), and bound to a thread-local slot
on the serving side so the front-door worker, the fair-queue dispatcher, and
the per-rank pvar op-scope can each open child spans without plumbing an
argument through every call signature.

Spans land in one process-global bounded buffer as plain dicts::

    {"trace": id, "span": sid, "parent": psid, "name": "...",
     "who": "client" | "router" | "broker" | "rank 3" | ...,
     "t0": monotonic, "t1": monotonic, "status": "ok" | "error", ...}

``analyze/timeline.py`` renders the buffer as Chrome-trace slices (one lane
per ``who``); multi-process runs dump per process via :func:`dump_spans`
and merge offline.

Overhead discipline matches ``analyze/events.enabled()``: an unsampled run
pays one tuple compare against ``config.GENERATION`` per op — no id
minting, no TLS writes, no metadata key.
"""

from __future__ import annotations

import itertools
import json
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import config
from . import locksmith

_UNSET = object()
_rate_cache: Tuple[Any, float] = (_UNSET, 0.0)


def sample_rate() -> float:
    """The effective TPU_MPI_TRACE_SAMPLE rate — cached on
    ``config.GENERATION`` so the untraced hot path is one tuple compare."""
    global _rate_cache
    cached_gen, val = _rate_cache
    if cached_gen == config.GENERATION:
        return val
    val = float(config.load().trace_sample)
    _rate_cache = (config.GENERATION, val)
    return val


def enabled() -> bool:
    """Whether request tracing can sample at all (rate > 0)."""
    return sample_rate() > 0.0


def sample() -> bool:
    """One sampling decision at trace-birth time (client session op)."""
    rate = sample_rate()
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    return random.random() < rate


# span-id minting: a per-process nonce + counter keeps ids unique across
# the processes one trace crosses without coordination.
_NONCE = os.urandom(3).hex()
_ids = itertools.count(1)


def _new_id() -> str:
    return f"{_NONCE}-{next(_ids)}"


class TraceCtx:
    """One request's position in its trace: where a child span attaches."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: str, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    @classmethod
    def mint(cls) -> "TraceCtx":
        """A fresh root context (trace birth, client side)."""
        return cls(os.urandom(8).hex(), _new_id(), True)

    def child(self) -> "TraceCtx":
        """A context one span deeper (the receiver side of a hop)."""
        return TraceCtx(self.trace_id, _new_id(), self.sampled)

    def to_meta(self) -> dict:
        """The compact frame-metadata carriage of this context."""
        return {"id": self.trace_id, "span": self.span_id,
                "s": 1 if self.sampled else 0}

    @classmethod
    def from_meta(cls, meta: Optional[dict]) -> Optional["TraceCtx"]:
        """Recover a context from frame metadata (None when untraced)."""
        t = (meta or {}).get("trace")
        if not isinstance(t, dict) or "id" not in t or "span" not in t:
            return None
        return cls(str(t["id"]), str(t["span"]), bool(t.get("s", 1)))

    def __repr__(self) -> str:
        return f"<TraceCtx {self.trace_id}/{self.span_id}>"


# ---------------------------------------------------------------------------
# Thread-local binding: the serving side's implicit context slot.
# ---------------------------------------------------------------------------

_tls = threading.local()


def current() -> Optional[TraceCtx]:
    """The TraceCtx bound to this thread (None when untraced)."""
    return getattr(_tls, "ctx", None)


class bind:
    """Context manager binding ``ctx`` (may be None) to this thread."""

    __slots__ = ("_ctx", "_prev")

    def __init__(self, ctx: Optional[TraceCtx]):
        self._ctx = ctx

    def __enter__(self) -> Optional[TraceCtx]:
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc) -> bool:
        _tls.ctx = self._prev
        return False


# ---------------------------------------------------------------------------
# Span buffer: process-global, bounded, drained by timeline export.
# ---------------------------------------------------------------------------

_SPAN_CAP = 8192
_spans_lock = locksmith.make_lock("tracectx.spans")
_spans: List[dict] = []
_spans_dropped = 0


def start_span(ctx: Optional[TraceCtx], name: str, who: str,
               **extra: Any) -> Optional[dict]:
    """Open a child span under ``ctx``; returns the record to pass to
    :func:`end_span`, or None when ``ctx`` is absent/unsampled. The record
    is NOT in the buffer until ended — an abandoned record costs nothing."""
    if ctx is None or not ctx.sampled:
        return None
    rec = {"trace": ctx.trace_id, "span": _new_id(), "parent": ctx.span_id,
           "name": name, "who": who, "t0": time.monotonic(), "t1": None,
           "status": "ok"}
    if extra:
        rec.update({k: v for k, v in extra.items() if v is not None})
    return rec


def start_root(name: str, who: str, **extra: Any):
    """Trace birth: one sampling decision, a fresh trace id, and the OPEN
    root span record. Returns ``(ctx, rec)`` — ``ctx.span_id`` is the root
    span itself, so downstream hops parent directly under it — or
    ``(None, None)`` when this request is not sampled."""
    if not sample():
        return None, None
    trace_id = os.urandom(8).hex()
    rec = {"trace": trace_id, "span": _new_id(), "parent": None,
           "name": name, "who": who, "t0": time.monotonic(), "t1": None,
           "status": "ok"}
    if extra:
        rec.update({k: v for k, v in extra.items() if v is not None})
    return TraceCtx(trace_id, rec["span"], True), rec


def end_span(rec: Optional[dict], status: str = "ok", **extra: Any) -> None:
    """Close and publish a span opened by :func:`start_span`."""
    if rec is None:
        return
    rec["t1"] = time.monotonic()
    rec["status"] = status
    if extra:
        rec.update(extra)
    global _spans_dropped
    with _spans_lock:
        if len(_spans) >= _SPAN_CAP:
            del _spans[:_SPAN_CAP // 4]          # drop the oldest quarter
            _spans_dropped += _SPAN_CAP // 4
        _spans.append(rec)


def emit_span(ctx: Optional[TraceCtx], name: str, who: str, t0: float,
              t1: float, status: str = "ok", **extra: Any) -> Optional[dict]:
    """Publish a span whose bracket was measured elsewhere (a queue wait
    reconstructed at pop time, a pvar op scope's phase spans). Returns the
    published record so callers can parent further children under it."""
    if ctx is None or not ctx.sampled:
        return None
    rec = {"trace": ctx.trace_id, "span": _new_id(), "parent": ctx.span_id,
           "name": name, "who": who, "t0": t0, "t1": t1, "status": status}
    if extra:
        rec.update(extra)
    global _spans_dropped
    with _spans_lock:
        if len(_spans) >= _SPAN_CAP:
            del _spans[:_SPAN_CAP // 4]
            _spans_dropped += _SPAN_CAP // 4
        _spans.append(rec)
    return rec


class span:
    """``with span(ctx, name, who): ...`` — the two calls above as a scope;
    an exception closes the span with error status (and propagates)."""

    __slots__ = ("_rec", "_args", "_kw")

    def __init__(self, ctx: Optional[TraceCtx], name: str, who: str,
                 **extra: Any):
        self._args = (ctx, name, who)
        self._kw = extra

    def __enter__(self) -> Optional[dict]:
        self._rec = start_span(*self._args, **self._kw)
        return self._rec

    def __exit__(self, et, ev, tb) -> bool:
        if et is None:
            end_span(self._rec)
        else:
            end_span(self._rec, status="error", error=type(ev).__name__)
        return False


def child_for_span(rec: Optional[dict],
                   ctx: Optional[TraceCtx]) -> Optional[TraceCtx]:
    """A TraceCtx whose children parent under an OPEN span record — how a
    hop makes its downstream work nest inside its own span."""
    if rec is None or ctx is None:
        return ctx
    return TraceCtx(rec["trace"], rec["span"], True)


def drain(trace_id: Optional[str] = None) -> List[dict]:
    """Snapshot (without clearing) the span buffer, optionally filtered to
    one trace. Single-process cpu-sim runs read their whole trace here."""
    with _spans_lock:
        out = list(_spans)
    if trace_id is not None:
        out = [s for s in out if s["trace"] == trace_id]
    return out


def reset() -> None:
    """Clear the buffer (test isolation)."""
    global _spans_dropped
    with _spans_lock:
        _spans.clear()
        _spans_dropped = 0


def dump_spans(path: str) -> str:
    """Write this process's span buffer as JSON; merge offline with
    :func:`load_spans` over several files."""
    with _spans_lock:
        payload = {"version": 1, "pid": os.getpid(),
                   "dropped": _spans_dropped, "spans": list(_spans)}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def load_spans(paths: Any) -> List[dict]:
    """Merge one or more span-dump files back into one span list."""
    if isinstance(paths, str):
        paths = [paths]
    out: List[dict] = []
    for p in paths:
        with open(p) as f:
            payload = json.load(f)
        out.extend(payload.get("spans", ()))
    return out
