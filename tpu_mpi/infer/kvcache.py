"""Paged KV-cache manager + cross-stage partition streaming (tpu_mpi.infer).

Two concerns live here, both per-rank state of the inference engine:

- :class:`PagedKVCache` — attention key/value storage in fixed-size token
  blocks (``TPU_MPI_KV_BLOCK_TOKENS`` wide) drawn from one preallocated
  pool, chained per ``(session, layer)``. Paging is what makes admission a
  counting problem: the scheduler admits a request iff the blocks its
  whole generation can touch are still free, so a full cache turns into
  queueing delay (and eventually a typed SLO eviction) instead of a
  mid-generation failure. Blocks are refcounted: with
  ``TPU_MPI_KV_PREFIX_SHARE`` on, a completed prefill publishes its
  prompt-prefix blocks into a content-hash registry
  (:meth:`~PagedKVCache.register_prefix`) and later sessions presenting
  the same prompt prefix adopt them read-only
  (:meth:`~PagedKVCache.prefix_acquire`) — the first append into a block
  someone else can still see forks a private copy (copy-on-write), so a
  sharer can never observe another tenant's writes. Isolation is a
  property of the admission layer: a session only ever matches prefixes
  of tokens it presented itself, and the KV rows behind a match are a
  pure function of those tokens and the model.
- :class:`PartitionStreamWriter` / :class:`PartitionStreamReader` — the
  prefill activation stream between pipeline stages, built on the MPI-4
  partitioned ops (``Psend_init``/``Pready`` producing,
  ``Precv_init``/``Parrived`` consuming). Stage k marks each block of
  prompt activations ready as it finishes computing it; stage k+1 starts
  attending over block p while block p+1 is still being produced. The
  reader accounts its blocked time (``wait_ns``) so the pvar infer block
  can show the overlap won over a serial stage hand-off.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..error import MPIError
from .. import error as _ec
from .. import locksmith


def _prefix_key(tokens: Sequence[int]) -> bytes:
    """Content hash of a token prefix (the registry key). Stored entries
    also keep the token tuple itself and compare it on lookup, so a hash
    collision can never splice one tenant's KV into another's prompt."""
    return hashlib.blake2b(np.asarray(tokens, np.int64).tobytes(),
                           digest_size=16).digest()


class PagedKVCache:
    """Block-paged K/V storage for one rank.

    ``n_blocks`` blocks of ``block_tokens`` tokens, each token a
    ``(n_heads, head_dim)`` K and V row. Chains grow one token at a time
    (:meth:`append`) and are read back as contiguous ``(t, h, dh)`` views
    (:meth:`view`). All methods are thread-safe; the scheduler reads
    :meth:`free_blocks` / :meth:`stats` while rank workers mutate.
    """

    def __init__(self, n_blocks: int, block_tokens: int, n_heads: int,
                 head_dim: int, dtype=np.float32):
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.k = np.zeros((n_blocks, block_tokens, n_heads, head_dim), dtype)
        self.v = np.zeros_like(self.k)
        # pop() from the tail: allocation order is a pure function of the
        # alloc/release history, never of timing
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._refs: Dict[int, int] = {}
        self._chains: Dict[Tuple[int, int], List[int]] = {}
        self._len: Dict[Tuple[int, int], int] = {}
        # content-hash prefix registry (LRU): key -> {"tokens": tuple,
        # "blocks": {layer: [ids]}, "partials": [{"tokens","blocks"}]}.
        # The registry holds one reference per block it can hand out.
        self._registry: "OrderedDict[bytes, dict]" = OrderedDict()
        self._lock = locksmith.make_lock("infer.kvcache")
        self.peak_in_use = 0
        self.alloc_failures = 0
        self.cow_forks = 0
        self.prefix_evictions = 0

    # -- block accounting (lock held) ----------------------------------------
    def _alloc_locked(self) -> int:
        if not self._free:
            self._evict_registry_locked()
        if not self._free:
            self.alloc_failures += 1
            raise MPIError(
                f"KV cache exhausted: {self.n_blocks} blocks all in "
                f"use (raise TPU_MPI_KV_BLOCK_TOKENS pool sizing or "
                f"lower TPU_MPI_INFER_MAX_BATCH)",
                code=_ec.ERR_BUFFER)
        b = self._free.pop()
        self._refs[b] = 1
        in_use = self.n_blocks - len(self._free)
        if in_use > self.peak_in_use:
            self.peak_in_use = in_use
        return b

    def _deref_locked(self, b: int) -> None:
        r = self._refs.get(b, 1) - 1
        if r <= 0:
            self._refs.pop(b, None)
            self._free.append(b)
        else:
            self._refs[b] = r

    def _evict_registry_locked(self) -> None:
        """Drop LRU registry entries until a block actually frees (or the
        registry is empty): the prefix cache yields under pool pressure,
        never the other way around."""
        while self._registry and not self._free:
            _, e = self._registry.popitem(last=False)
            self.prefix_evictions += 1
            for blocks in e["blocks"].values():
                for b in blocks:
                    self._deref_locked(b)
            for ch in e["partials"]:
                for b in ch["blocks"].values():
                    self._deref_locked(b)

    # -- chains ---------------------------------------------------------------
    def append(self, sid: int, layer: int, k_row: np.ndarray,
               v_row: np.ndarray) -> None:
        """Append one token's ``(h, dh)`` K/V rows to a chain, growing it
        by a fresh block on a block boundary. Appending into a block that
        anyone else can still see (another chain or the prefix registry)
        forks a private copy first — copy-on-write."""
        key = (sid, layer)
        B = self.block_tokens
        with self._lock:
            n = self._len.get(key, 0)
            chain = self._chains.setdefault(key, [])
            if n % B == 0 and n // B == len(chain):
                chain.append(self._alloc_locked())
            bi = n // B
            b = chain[bi]
            if self._refs.get(b, 1) > 1:
                nb = self._alloc_locked()
                self.k[nb] = self.k[b]
                self.v[nb] = self.v[b]
                self._deref_locked(b)
                chain[bi] = nb
                self.cow_forks += 1
                b = nb
            off = n % B
            self.k[b, off] = k_row
            self.v[b, off] = v_row
            self._len[key] = n + 1

    def view(self, sid: int, layer: int) -> Tuple[np.ndarray, np.ndarray]:
        """The chain's K and V as dense ``(t, h, dh)`` arrays (copies —
        the caller attends over a stable snapshot)."""
        key = (sid, layer)
        with self._lock:
            n = self._len.get(key, 0)
            chain = list(self._chains.get(key, ()))
            B = self.block_tokens
            out_k = np.empty((n,) + self.k.shape[2:], self.k.dtype)
            out_v = np.empty_like(out_k)
            for i, b in enumerate(chain):
                lo = i * B
                take = min(B, n - lo)
                if take <= 0:
                    break
                out_k[lo:lo + take] = self.k[b, :take]
                out_v[lo:lo + take] = self.v[b, :take]
        return out_k, out_v

    def length(self, sid: int, layer: int) -> int:
        with self._lock:
            return self._len.get((sid, layer), 0)

    def truncate(self, sid: int, new_len: int) -> None:
        """Roll every chain of one session back to at most ``new_len``
        tokens (the speculative-decode rejection rollback). Whole blocks
        past the boundary are dereferenced; a surviving tail block that is
        still shared simply stays read-only until the next append forks
        it."""
        B = self.block_tokens
        with self._lock:
            for key in [k for k in self._chains if k[0] == sid]:
                n = self._len.get(key, 0)
                if n <= new_len:
                    continue
                chain = self._chains[key]
                keep = math.ceil(new_len / B)
                for b in reversed(chain[keep:]):
                    self._deref_locked(b)
                del chain[keep:]
                self._len[key] = new_len

    def close(self, sid: int) -> int:
        """Release every chain of one session; returns blocks dropped
        from its chains (shared blocks survive under their remaining
        references)."""
        freed = 0
        with self._lock:
            for key in [k for k in self._chains if k[0] == sid]:
                chain = self._chains.pop(key)
                self._len.pop(key, None)
                for b in reversed(chain):
                    self._deref_locked(b)
                freed += len(chain)
        return freed

    # -- cross-tenant prefix sharing ------------------------------------------
    def register_prefix(self, sid: int, tokens: Sequence[int]) -> None:
        """Publish session ``sid``'s prompt-prefix KV into the registry:
        one entry per full-block boundary (so a later prompt that
        diverges anywhere can still match its longest agreeing boundary),
        each holding its prefix blocks by reference plus a *continuation
        child* — the next block's tokens — for mid-block matches. Full
        blocks are referenced as-is (prefill never writes into a
        completed full block again, so they are immutable); the trailing
        partial block is COPIED so the owner keeps appending into its own
        tail without a fork."""
        toks = tuple(int(t) for t in tokens)
        B = self.block_tokens
        nfull = len(toks) // B
        if nfull == 0:
            return
        with self._lock:
            layers = sorted(k[1] for k in self._chains if k[0] == sid)
            if not layers or any(len(self._chains[(sid, li)]) * B
                                 < len(toks) for li in layers):
                return
            for j in range(1, nfull + 1):
                key = _prefix_key(toks[:j * B])
                e = self._registry.get(key)
                if e is None or e["tokens"] != toks[:j * B]:
                    blocks = {li: list(self._chains[(sid, li)][:j])
                              for li in layers}
                    for bl in blocks.values():
                        for b in bl:
                            self._refs[b] = self._refs.get(b, 1) + 1
                    e = {"tokens": toks[:j * B], "blocks": blocks,
                         "partials": []}
                    self._registry[key] = e
                self._registry.move_to_end(key)
                cont = toks[j * B:min((j + 1) * B, len(toks))]
                if not cont or any(ch["tokens"][:len(cont)] == cont
                                   for ch in e["partials"]
                                   if len(ch["tokens"]) >= len(cont)):
                    continue
                if j < nfull:
                    # continuation is a completed (immutable) full block:
                    # share it by reference
                    pblocks = {}
                    for li in layers:
                        b = self._chains[(sid, li)][j]
                        self._refs[b] = self._refs.get(b, 1) + 1
                        pblocks[li] = b
                else:
                    # trailing partial: the owner still appends into it —
                    # copy, so neither side ever needs a fork for it
                    pblocks = {}
                    try:
                        for li in layers:
                            src = self._chains[(sid, li)][j]
                            nb = self._alloc_locked()
                            self.k[nb] = self.k[src]
                            self.v[nb] = self.v[src]
                            pblocks[li] = nb
                    except MPIError:
                        self.alloc_failures -= 1  # pressure: skip, not fail
                        for b in pblocks.values():
                            self._deref_locked(b)
                        continue
                e["partials"].append({"tokens": cont, "blocks": pblocks})

    def prefix_acquire(self, sid: int, tokens: Sequence[int]) -> int:
        """Adopt the longest registered shared prefix of ``tokens`` as the
        initial chains for session ``sid``, capped at ``len(tokens) - 1``
        (the final prompt token is always recomputed so prefill still
        produces the first sampled hidden state). Returns the adopted
        token count (0 = miss). Adopted blocks are read-only references;
        the first divergent append copy-on-writes."""
        toks = tuple(int(t) for t in tokens)
        cap = len(toks) - 1
        B = self.block_tokens
        with self._lock:
            for j in range(len(toks) // B, 0, -1):
                key = _prefix_key(toks[:j * B])
                e = self._registry.get(key)
                if e is None or e["tokens"] != toks[:j * B]:
                    continue
                base = min(j * B, cap)
                best, best_len = None, 0
                if base == j * B:
                    for ch in e["partials"]:
                        L = 0
                        for a, b in zip(ch["tokens"], toks[j * B:]):
                            if a != b:
                                break
                            L += 1
                        L = min(L, cap - j * B)
                        if L > best_len:
                            best, best_len = ch, L
                adopted = base + best_len
                if adopted <= 0:
                    continue
                nb_full = min(j, math.ceil(adopted / B))
                for li, blocks in e["blocks"].items():
                    chain = list(blocks[:nb_full])
                    for b in chain:
                        self._refs[b] = self._refs.get(b, 1) + 1
                    if best is not None and best_len:
                        pb = best["blocks"][li]
                        self._refs[pb] = self._refs.get(pb, 1) + 1
                        chain.append(pb)
                    self._chains[(sid, li)] = chain
                    self._len[(sid, li)] = adopted
                self._registry.move_to_end(key)
                return adopted
        return 0

    # -- reporting ------------------------------------------------------------
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def stats(self) -> dict:
        with self._lock:
            in_use = self.n_blocks - len(self._free)
            shared = sum(1 for r in self._refs.values() if r > 1)
            return {"blocks": self.n_blocks,
                    "block_tokens": self.block_tokens,
                    "in_use": in_use, "peak_in_use": self.peak_in_use,
                    "chains": len(self._chains),
                    "alloc_failures": self.alloc_failures,
                    "shared_blocks": shared,
                    "prefix_entries": len(self._registry),
                    "prefix_evictions": self.prefix_evictions,
                    "cow_forks": self.cow_forks}


class PartitionStreamWriter:
    """Producer side of the stage k -> k+1 prefill activation stream: a
    partitioned send whose partitions are token blocks. :meth:`publish`
    copies one finished block into the send buffer and ``Pready``s it —
    the partition ships immediately, while later blocks are still being
    computed."""

    def __init__(self, nparts: int, block_tokens: int, width: int,
                 dest: int, tag: int, comm):
        from .. import pointtopoint as p2p
        self.nparts = int(nparts)
        self.block_tokens = int(block_tokens)
        self.buf = np.zeros((self.nparts * self.block_tokens, width),
                            np.float32)
        self._req = p2p.Psend_init(self.buf, self.nparts, dest, tag, comm)
        self._req.start()

    def publish(self, p: int, rows: np.ndarray) -> None:
        o = p * self.block_tokens
        k = rows.shape[0]
        if k:
            self.buf[o:o + k] = rows
        self._req.pready(p)

    def finish(self) -> None:
        self._req.wait()


class PartitionStreamReader:
    """Consumer side: a partitioned receive polled one token block at a
    time. :meth:`take` blocks until partition ``p`` has arrived and
    returns its rows; the time spent blocked accumulates in ``wait_ns`` —
    the overlap evidence (a reader that waits much less than the producer
    computes is consuming behind the producer, not after it)."""

    def __init__(self, nparts: int, block_tokens: int, width: int,
                 src: int, tag: int, comm):
        from .. import pointtopoint as p2p
        self.nparts = int(nparts)
        self.block_tokens = int(block_tokens)
        self.buf = np.zeros((self.nparts * self.block_tokens, width),
                            np.float32)
        self._req = p2p.Precv_init(self.buf, self.nparts, src, tag, comm)
        self._req.start()
        self.wait_ns = 0

    def take(self, p: int) -> np.ndarray:
        t0 = time.perf_counter_ns()
        while not self._req.parrived(p):
            time.sleep(0)
        self.wait_ns += time.perf_counter_ns() - t0
        o = p * self.block_tokens
        return self.buf[o:o + self.block_tokens]

    def finish(self) -> None:
        self._req.wait()
