"""Paged KV-cache manager + cross-stage partition streaming (tpu_mpi.infer).

Two concerns live here, both per-rank state of the inference engine:

- :class:`PagedKVCache` — attention key/value storage in fixed-size token
  blocks (``TPU_MPI_KV_BLOCK_TOKENS`` wide) drawn from one preallocated
  pool, chained per ``(session, layer)``. Paging is what makes admission a
  counting problem: the scheduler admits a request iff the blocks its
  whole generation can touch are still free, so a full cache turns into
  queueing delay (and eventually a typed SLO eviction) instead of a
  mid-generation failure.
- :class:`PartitionStreamWriter` / :class:`PartitionStreamReader` — the
  prefill activation stream between pipeline stages, built on the MPI-4
  partitioned ops (``Psend_init``/``Pready`` producing,
  ``Precv_init``/``Parrived`` consuming). Stage k marks each block of
  prompt activations ready as it finishes computing it; stage k+1 starts
  attending over block p while block p+1 is still being produced. The
  reader accounts its blocked time (``wait_ns``) so the pvar infer block
  can show the overlap won over a serial stage hand-off.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

import numpy as np

from ..error import MPIError
from .. import error as _ec


class PagedKVCache:
    """Block-paged K/V storage for one rank.

    ``n_blocks`` blocks of ``block_tokens`` tokens, each token a
    ``(n_heads, head_dim)`` K and V row. Chains grow one token at a time
    (:meth:`append`) and are read back as contiguous ``(t, h, dh)`` views
    (:meth:`view`). All methods are thread-safe; the scheduler reads
    :meth:`free_blocks` / :meth:`stats` while rank workers mutate.
    """

    def __init__(self, n_blocks: int, block_tokens: int, n_heads: int,
                 head_dim: int, dtype=np.float32):
        self.n_blocks = int(n_blocks)
        self.block_tokens = int(block_tokens)
        self.k = np.zeros((n_blocks, block_tokens, n_heads, head_dim), dtype)
        self.v = np.zeros_like(self.k)
        # pop() from the tail: allocation order is a pure function of the
        # alloc/release history, never of timing
        self._free: List[int] = list(range(self.n_blocks - 1, -1, -1))
        self._chains: Dict[Tuple[int, int], List[int]] = {}
        self._len: Dict[Tuple[int, int], int] = {}
        self._lock = threading.Lock()
        self.peak_in_use = 0
        self.alloc_failures = 0

    def append(self, sid: int, layer: int, k_row: np.ndarray,
               v_row: np.ndarray) -> None:
        """Append one token's ``(h, dh)`` K/V rows to a chain, growing it
        by a fresh block on a block boundary."""
        key = (sid, layer)
        with self._lock:
            n = self._len.get(key, 0)
            chain = self._chains.setdefault(key, [])
            if n % self.block_tokens == 0:
                if not self._free:
                    self.alloc_failures += 1
                    raise MPIError(
                        f"KV cache exhausted: {self.n_blocks} blocks all in "
                        f"use (raise TPU_MPI_KV_BLOCK_TOKENS pool sizing or "
                        f"lower TPU_MPI_INFER_MAX_BATCH)",
                        code=_ec.ERR_BUFFER)
                chain.append(self._free.pop())
                in_use = self.n_blocks - len(self._free)
                if in_use > self.peak_in_use:
                    self.peak_in_use = in_use
            b, off = chain[n // self.block_tokens], n % self.block_tokens
            self.k[b, off] = k_row
            self.v[b, off] = v_row
            self._len[key] = n + 1

    def view(self, sid: int, layer: int) -> Tuple[np.ndarray, np.ndarray]:
        """The chain's K and V as dense ``(t, h, dh)`` arrays (copies —
        the caller attends over a stable snapshot)."""
        key = (sid, layer)
        with self._lock:
            n = self._len.get(key, 0)
            chain = list(self._chains.get(key, ()))
            B = self.block_tokens
            out_k = np.empty((n,) + self.k.shape[2:], self.k.dtype)
            out_v = np.empty_like(out_k)
            for i, b in enumerate(chain):
                lo = i * B
                take = min(B, n - lo)
                if take <= 0:
                    break
                out_k[lo:lo + take] = self.k[b, :take]
                out_v[lo:lo + take] = self.v[b, :take]
        return out_k, out_v

    def length(self, sid: int, layer: int) -> int:
        with self._lock:
            return self._len.get((sid, layer), 0)

    def close(self, sid: int) -> int:
        """Release every chain of one session; returns blocks freed."""
        freed = 0
        with self._lock:
            for key in [k for k in self._chains if k[0] == sid]:
                chain = self._chains.pop(key)
                self._len.pop(key, None)
                self._free.extend(reversed(chain))
                freed += len(chain)
        return freed

    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    def stats(self) -> dict:
        with self._lock:
            in_use = self.n_blocks - len(self._free)
            return {"blocks": self.n_blocks,
                    "block_tokens": self.block_tokens,
                    "in_use": in_use, "peak_in_use": self.peak_in_use,
                    "chains": len(self._chains),
                    "alloc_failures": self.alloc_failures}


class PartitionStreamWriter:
    """Producer side of the stage k -> k+1 prefill activation stream: a
    partitioned send whose partitions are token blocks. :meth:`publish`
    copies one finished block into the send buffer and ``Pready``s it —
    the partition ships immediately, while later blocks are still being
    computed."""

    def __init__(self, nparts: int, block_tokens: int, width: int,
                 dest: int, tag: int, comm):
        from .. import pointtopoint as p2p
        self.nparts = int(nparts)
        self.block_tokens = int(block_tokens)
        self.buf = np.zeros((self.nparts * self.block_tokens, width),
                            np.float32)
        self._req = p2p.Psend_init(self.buf, self.nparts, dest, tag, comm)
        self._req.start()

    def publish(self, p: int, rows: np.ndarray) -> None:
        o = p * self.block_tokens
        k = rows.shape[0]
        if k:
            self.buf[o:o + k] = rows
        self._req.pready(p)

    def finish(self) -> None:
        self._req.wait()


class PartitionStreamReader:
    """Consumer side: a partitioned receive polled one token block at a
    time. :meth:`take` blocks until partition ``p`` has arrived and
    returns its rows; the time spent blocked accumulates in ``wait_ns`` —
    the overlap evidence (a reader that waits much less than the producer
    computes is consuming behind the producer, not after it)."""

    def __init__(self, nparts: int, block_tokens: int, width: int,
                 src: int, tag: int, comm):
        from .. import pointtopoint as p2p
        self.nparts = int(nparts)
        self.block_tokens = int(block_tokens)
        self.buf = np.zeros((self.nparts * self.block_tokens, width),
                            np.float32)
        self._req = p2p.Precv_init(self.buf, self.nparts, src, tag, comm)
        self._req.start()
        self.wait_ns = 0

    def take(self, p: int) -> np.ndarray:
        t0 = time.perf_counter_ns()
        while not self._req.parrived(p):
            time.sleep(0)
        self.wait_ns += time.perf_counter_ns() - t0
        o = p * self.block_tokens
        return self.buf[o:o + self.block_tokens]

    def finish(self) -> None:
        self._req.wait()
