"""Continuous-batching request scheduler for the inference engine.

One daemon thread turns the request stream into :class:`StepPlan`s:

- **Admission**: requests queue FIFO per arrival (the broker's FairQueue
  already ordered them across tenants); the head is admitted when a batch
  slot AND its whole KV-block demand on its home pair are free — paged-KV
  backpressure becomes queueing delay, never a mid-generation failure.
- **SLO eviction**: with ``TPU_MPI_INFER_SLO_MS`` set, a request still
  *pending* past its deadline is evicted with the typed, retriable
  :class:`~tpu_mpi.error.SLOExpiredError`; a request that completes is
  booked as an SLO hit or miss against the same deadline.
- **Continuous batching**: every step co-schedules the newly admitted
  prefills with every in-flight decode — one engine step, one new token
  per active request. Finished/cancelled sessions ride out in the plan's
  release list so every rank frees their KV chains in lockstep.

Token values never depend on what else is in a batch (the engine's
row-wise contract), so greedy sequences are bitwise identical whether
requests arrive together or staggered.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .. import config
from .. import error as _ec
from .. import perfvars
from ..error import MPIError, SessionError, SLOExpiredError
from .engine import Decode, InferEngine, Prefill, StepPlan, PREFILL_TAG_BASE

monotonic = time.monotonic


class InferRequest:
    """One generation request and its outbound token stream. The broker
    handler thread consumes ``out``: ("tok", [ids]) chunks, then one
    ("done", info) or ("err", exception)."""

    __slots__ = ("rid", "tenant", "prompt", "max_new", "slot", "kv_need",
                 "tag", "slo_ms", "deadline", "submitted", "pos",
                 "generated", "out", "state")

    def __init__(self, rid: int, tenant: str, prompt: List[int],
                 max_new: int, slot: int, kv_need: int, slo_ms: int):
        self.rid = rid
        self.tenant = tenant
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.slot = slot
        self.kv_need = kv_need
        self.tag = 0
        self.slo_ms = int(slo_ms)
        self.submitted = monotonic()
        self.deadline = (self.submitted + self.slo_ms / 1e3
                         if self.slo_ms > 0 else None)
        self.pos = 0                      # next feed position (set at prefill)
        self.generated: List[int] = []
        self.out: "queue.Queue" = queue.Queue()
        self.state = "pending"

    def fail(self, exc: BaseException) -> None:
        if self.state in ("done", "failed"):
            return
        self.state = "failed"
        self.out.put(("err", exc))

    def finish(self, info: dict) -> None:
        self.state = "done"
        self.out.put(("done", info))


class InferScheduler:
    """The continuous-batching loop over one :class:`InferEngine`."""

    def __init__(self, engine: InferEngine, *,
                 max_batch: Optional[int] = None,
                 slo_ms: Optional[int] = None):
        knobs = config.load()
        self.engine = engine
        self.max_batch = max(1, int(engine.max_batch if max_batch is None
                                    else max_batch))
        self.slo_ms = int(knobs.infer_slo_ms if slo_ms is None else slo_ms)
        self._lock = threading.Lock()
        self._pending: Deque[InferRequest] = deque()
        self._active: List[InferRequest] = []
        self._releases: List[InferRequest] = []
        self._rid = itertools.count(1)
        self._seq = itertools.count(0)
        self._stream = itertools.count(0)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._paused = threading.Event()     # elastic quiesce requested
        self._boundary = threading.Event()   # loop parked between steps
        self._dead: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self.counters = {"admitted": 0, "completed": 0, "cancelled": 0,
                         "slo_evictions": 0, "slo_hits": 0, "slo_misses": 0,
                         "steps": 0, "step_ns": 0, "tokens": 0,
                         "batch_slots": 0, "prefill_tokens": 0}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="infer-sched", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        exc = SessionError("inference engine shutting down")
        with self._lock:
            doomed = list(self._pending) + list(self._active)
            self._pending.clear()
            self._active.clear()
        for r in doomed:
            r.fail(exc)

    # -- intake --------------------------------------------------------------
    def submit(self, tenant: str, prompt: List[int],
               max_new: int) -> InferRequest:
        """Queue one generation request (validation is the broker's job);
        returns immediately — tokens stream through ``req.out``."""
        if self._dead is not None:
            raise MPIError(f"inference engine is down: {self._dead}",
                           code=_ec.ERR_OTHER)
        rid = next(self._rid)
        slot = (rid - 1) % self.engine.ep
        need = self.engine.kv_demand(len(prompt), max_new)
        req = InferRequest(rid, tenant, prompt, max_new, slot, need,
                           self.slo_ms)
        with self._lock:
            self._pending.append(req)
        self._wake.set()
        return req

    def cancel_tenant(self, tenant: str) -> int:
        """Evict every request of a revoked tenant: pending ones fail
        immediately, in-flight ones leave the batch and their KV chains
        are released on the next step. Survivor tenants never notice."""
        exc = SessionError(f"lease for tenant {tenant!r} revoked "
                           f"mid-generation")
        with self._lock:
            dropped = [r for r in self._pending if r.tenant == tenant]
            self._pending = deque(r for r in self._pending
                                  if r.tenant != tenant)
            victims = [r for r in self._active if r.tenant == tenant]
            self._active = [r for r in self._active if r.tenant != tenant]
            for r in victims:
                r.state = "cancelled"
                self._releases.append(r)
            self.counters["cancelled"] += len(dropped) + len(victims)
        for r in dropped + victims:
            r.fail(exc)
        self._wake.set()
        return len(dropped) + len(victims)

    # -- the batching loop ---------------------------------------------------
    def _evict_expired(self, now: float) -> None:
        still: Deque[InferRequest] = deque()
        for r in self._pending:
            if r.deadline is not None and now > r.deadline:
                self.counters["slo_evictions"] += 1
                if perfvars.enabled():
                    perfvars.note_infer(slo_evictions=1)
                r.fail(SLOExpiredError(
                    f"request rid={r.rid} waited past its "
                    f"{r.slo_ms}ms SLO deadline without being scheduled "
                    f"(engine saturated) — retry under lighter load",
                    tenant=r.tenant, rid=r.rid, slo_ms=r.slo_ms))
            else:
                still.append(r)
        self._pending = still

    def _build_plan(self) -> Optional[tuple]:
        """Under the lock: evict, admit, snapshot one step. Returns
        (plan, prefills, decodes) or None when there is nothing to do."""
        self._evict_expired(monotonic())
        prefills: List[InferRequest] = []
        while (self._pending
               and len(self._active) + len(prefills) < self.max_batch):
            head = self._pending[0]
            if not self.engine.can_admit(head.slot, head.kv_need):
                break                     # KV backpressure: FIFO holds
            self._pending.popleft()
            self.engine.reserve(head.slot, head.kv_need)
            head.tag = PREFILL_TAG_BASE + next(self._stream) % 4096
            head.state = "running"
            self.counters["admitted"] += 1
            prefills.append(head)
        decodes = list(self._active)
        releases = self._releases
        self._releases = []
        if not prefills and not decodes and not releases:
            self._wake.clear()
            return None
        plan = StepPlan(next(self._seq),
                        [Prefill(r.rid, r.slot, r.prompt, r.tag)
                         for r in prefills],
                        [Decode(r.rid, r.slot, r.generated[-1], r.pos)
                         for r in decodes],
                        [r.rid for r in releases])
        return plan, prefills, decodes, releases

    def pause(self, timeout: float = 30.0) -> bool:
        """Park the batching loop at a step boundary (the elastic rebind
        quiesce): requests keep queueing and SLO deadlines keep ticking —
        a request whose deadline passes while paused is evicted at resume —
        but nothing touches the engine until :meth:`resume`. Returns True
        once the loop is parked (no step mid-flight)."""
        self._paused.set()
        self._wake.set()
        return self._boundary.wait(timeout)

    def resume(self) -> None:
        self._paused.clear()
        self._boundary.clear()
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.2)
            if self._stop.is_set():
                return
            if self._paused.is_set():
                self._boundary.set()
                time.sleep(0.01)
                continue
            with self._lock:
                built = self._build_plan()
            if built is None:
                continue
            plan, prefills, decodes, releases = built
            t0 = time.perf_counter_ns()
            try:
                results = self.engine.run_step(plan)
            except BaseException as e:      # noqa: BLE001 - engine is down
                self._dead = e
                with self._lock:
                    doomed = prefills + decodes + list(self._pending)
                    self._pending.clear()
                    self._active.clear()
                for r in doomed:
                    r.fail(e if isinstance(e, MPIError) else
                           MPIError(f"inference step failed: {e!r}",
                                    code=_ec.ERR_OTHER))
                return
            step_ns = time.perf_counter_ns() - t0
            self._book_step(plan, prefills, decodes, releases, results,
                            step_ns)

    def _book_step(self, plan, prefills, decodes, releases, results,
                   step_ns) -> None:
        emitted = 0
        now = monotonic()
        with self._lock:
            for r in releases:
                self.engine.unreserve(r.slot, r.kv_need)
            for r in prefills:
                r.pos = len(r.prompt)     # first decode feeds at this pos
            for r in prefills + decodes:
                if r.state != "running":
                    continue              # cancelled while the step ran
                tok = results.get(r.rid)
                if tok is None:
                    continue
                if r in prefills:
                    self._active.append(r)
                else:
                    r.pos += 1
                r.generated.append(tok)
                emitted += 1
                r.out.put(("tok", [tok]))
                if len(r.generated) >= r.max_new:
                    self._active.remove(r)
                    self._releases.append(r)
                    hit = r.deadline is None or now <= r.deadline
                    self.counters["slo_hits" if hit else "slo_misses"] += 1
                    self.counters["completed"] += 1
                    if perfvars.enabled():
                        perfvars.note_infer(
                            **{"slo_hits" if hit else "slo_misses": 1})
                    r.finish({"total_tokens": len(r.generated),
                              "slo_hit": hit,
                              "latency_ms": round((now - r.submitted) * 1e3,
                                                  3)})
            self.counters["steps"] += 1
            self.counters["step_ns"] += step_ns
            self.counters["tokens"] += emitted
            self.counters["batch_slots"] += len(prefills) + len(decodes)
            self.counters["prefill_tokens"] += sum(len(r.prompt)
                                                   for r in prefills)
            if self._pending or self._releases:
                self._wake.set()
        if perfvars.enabled():
            perfvars.note_infer(steps=1, step_ns=step_ns, tokens=emitted,
                                batch_slots=len(prefills) + len(decodes),
                                prefills=len(prefills))
            kv = self.engine.kv_stats()
            perfvars.set_infer_gauges(
                max_batch=self.max_batch,
                kv_blocks_per_rank=kv["blocks_per_rank"],
                kv_in_use_max=kv["in_use_max"],
                kv_peak_in_use_max=kv["peak_in_use_max"],
                kv_alloc_failures=kv["alloc_failures"])

    # -- reporting -----------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            c = dict(self.counters)
            pending, active = len(self._pending), len(self._active)
        finished = c["slo_hits"] + c["slo_misses"]
        decode_s = c["step_ns"] / 1e9
        return {
            "max_batch": self.max_batch, "slo_ms": self.slo_ms,
            "pending": pending, "active": active,
            "paused": self._paused.is_set(), **c,
            "tokens_per_s": (round(c["tokens"] / decode_s, 3)
                             if decode_s > 0 else None),
            "batch_occupancy": (round(c["batch_slots"]
                                      / (c["steps"] * self.max_batch), 4)
                                if c["steps"] else None),
            "slo_hit_rate": (round(c["slo_hits"] / finished, 4)
                             if finished else None),
            "kv": self.engine.kv_stats(),
        }
