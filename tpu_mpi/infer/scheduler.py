"""Continuous-batching request scheduler for the inference engine.

One daemon thread turns the request stream into :class:`StepPlan`s:

- **Admission**: requests queue FIFO per arrival (the broker's FairQueue
  already ordered them across tenants); the head is admitted when a batch
  slot AND its whole KV-block demand on its home pair are free — paged-KV
  backpressure becomes queueing delay, never a mid-generation failure.
  With ``TPU_MPI_KV_PREFIX_SHARE`` on, admission is also where a request
  adopts registered shared-prefix KV blocks (read-only, copy-on-write):
  the isolation boundary is that a session can only ever match prefixes
  of tokens it presented itself.
- **SLO eviction**: with ``TPU_MPI_INFER_SLO_MS`` set, a request still
  *pending* past its deadline is evicted with the typed, retriable
  :class:`~tpu_mpi.error.SLOExpiredError`; a request that completes is
  booked as an SLO hit or miss against the same deadline.
- **Continuous batching**: every step co-schedules prefill chunks with
  every in-flight decode. ``TPU_MPI_INFER_PREFILL_CHUNK`` bounds the
  prefill tokens per step, splitting giant prompts across consecutive
  plans so they cannot head-of-line-block co-batched decodes.
- **Speculative drafting**: with ``TPU_MPI_INFER_SPEC_K`` > 1, each
  decode feeds up to k rows — the last accepted token plus drafts walked
  from the request's own bigram history (last-occurrence-wins, a pure
  function of its own stream). The engine accepts the greedy-matching
  prefix, so several tokens can ride one round of collectives.

Token values never depend on what else is in a batch (the engine's
row-wise contract), so greedy sequences are bitwise identical whether
requests arrive together or staggered, speculated or not.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from .. import config
from .. import error as _ec
from .. import locksmith
from .. import perfvars
from ..error import MPIError, SessionError, SLOExpiredError
from .engine import Decode, InferEngine, Prefill, StepPlan, PREFILL_TAG_BASE

monotonic = time.monotonic


class InferRequest:
    """One generation request and its outbound token stream. The broker
    handler thread consumes ``out``: ("tok", [ids]) chunks, then one
    ("done", info) or ("err", exception)."""

    __slots__ = ("rid", "tenant", "prompt", "max_new", "slot", "kv_need",
                 "tag", "slo_ms", "deadline", "submitted", "pos",
                 "generated", "out", "state", "pf_done", "pf_chunk",
                 "draft", "spec_fed", "trace")

    def __init__(self, rid: int, tenant: str, prompt: List[int],
                 max_new: int, slot: int, kv_need: int, slo_ms: int):
        self.rid = rid
        self.tenant = tenant
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.slot = slot
        self.kv_need = kv_need
        self.tag = 0
        self.slo_ms = int(slo_ms)
        self.submitted = monotonic()
        self.deadline = (self.submitted + self.slo_ms / 1e3
                         if self.slo_ms > 0 else None)
        self.pos = 0                      # next feed position (set at prefill)
        self.pf_done = 0                  # prompt tokens already in KV
        self.pf_chunk = 0                 # tokens in the in-flight chunk
        self.generated: List[int] = []
        # bigram draft table over this request's own stream
        # (last-occurrence-wins); seeded from the prompt
        self.draft: Dict[int, int] = {a: b for a, b
                                      in zip(self.prompt, self.prompt[1:])}
        self.spec_fed = 1
        self.out: "queue.Queue" = queue.Queue()
        self.state = "pending"
        self.trace = None                 # TraceCtx of a sampled request

    def fail(self, exc: BaseException) -> None:
        if self.state in ("done", "failed"):
            return
        self.state = "failed"
        self.out.put(("err", exc))

    def finish(self, info: dict) -> None:
        self.state = "done"
        self.out.put(("done", info))


class InferScheduler:
    """The continuous-batching loop over one :class:`InferEngine`."""

    def __init__(self, engine: InferEngine, *,
                 max_batch: Optional[int] = None,
                 slo_ms: Optional[int] = None):
        knobs = config.load()
        self.engine = engine
        self.max_batch = max(1, int(engine.max_batch if max_batch is None
                                    else max_batch))
        self.slo_ms = int(knobs.infer_slo_ms if slo_ms is None else slo_ms)
        self._lock = locksmith.make_lock("infer.scheduler")
        self._pending: Deque[InferRequest] = deque()
        self._prefilling: List[InferRequest] = []
        self._active: List[InferRequest] = []
        self._releases: List[InferRequest] = []
        self._rid = itertools.count(1)
        self._seq = itertools.count(0)
        self._stream = itertools.count(0)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._paused = threading.Event()     # elastic quiesce requested
        self._boundary = threading.Event()   # loop parked between steps
        self._dead: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self.counters = {"admitted": 0, "completed": 0, "cancelled": 0,
                         "slo_evictions": 0, "slo_hits": 0, "slo_misses": 0,
                         "steps": 0, "step_ns": 0, "tokens": 0,
                         "batch_slots": 0, "prefill_tokens": 0,
                         "spec_drafted": 0, "spec_accepted": 0,
                         "prefix_hit_tokens": 0, "prefix_miss_tokens": 0}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop,
                                        name="infer-sched", daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        exc = SessionError("inference engine shutting down")
        with self._lock:
            doomed = (list(self._pending) + list(self._prefilling)
                      + list(self._active))
            self._pending.clear()
            self._prefilling.clear()
            self._active.clear()
        for r in doomed:
            r.fail(exc)

    # -- intake --------------------------------------------------------------
    def submit(self, tenant: str, prompt: List[int],
               max_new: int, tctx=None) -> InferRequest:
        """Queue one generation request (validation is the broker's job);
        returns immediately — tokens stream through ``req.out``."""
        if self._dead is not None:
            raise MPIError(f"inference engine is down: {self._dead}",
                           code=_ec.ERR_OTHER)
        rid = next(self._rid)
        slot = (rid - 1) % self.engine.ep
        need = self.engine.kv_demand(len(prompt), max_new)
        req = InferRequest(rid, tenant, prompt, max_new, slot, need,
                           self.slo_ms)
        req.trace = tctx
        with self._lock:
            self._pending.append(req)
        self._wake.set()
        return req

    def cancel_tenant(self, tenant: str) -> int:
        """Evict every request of a revoked tenant: pending ones fail
        immediately, in-flight ones leave the batch and their KV chains
        are released on the next step. Survivor tenants never notice —
        shared prefix blocks they adopted stay alive under their own
        references."""
        exc = SessionError(f"lease for tenant {tenant!r} revoked "
                           f"mid-generation")
        with self._lock:
            dropped = [r for r in self._pending if r.tenant == tenant]
            self._pending = deque(r for r in self._pending
                                  if r.tenant != tenant)
            victims = [r for r in self._prefilling + self._active
                       if r.tenant == tenant]
            self._prefilling = [r for r in self._prefilling
                                if r.tenant != tenant]
            self._active = [r for r in self._active if r.tenant != tenant]
            for r in victims:
                r.state = "cancelled"
                self._releases.append(r)
            self.counters["cancelled"] += len(dropped) + len(victims)
        for r in dropped + victims:
            r.fail(exc)
        self._wake.set()
        return len(dropped) + len(victims)

    # -- the batching loop ---------------------------------------------------
    def _evict_expired(self, now: float) -> None:
        still: Deque[InferRequest] = deque()
        for r in self._pending:
            if r.deadline is not None and now > r.deadline:
                self.counters["slo_evictions"] += 1
                if perfvars.enabled():
                    perfvars.note_infer(slo_evictions=1)
                r.fail(SLOExpiredError(
                    f"request rid={r.rid} waited past its "
                    f"{r.slo_ms}ms SLO deadline without being scheduled "
                    f"(engine saturated) — retry under lighter load",
                    tenant=r.tenant, rid=r.rid, slo_ms=r.slo_ms))
            else:
                still.append(r)
        self._pending = still

    def _draft_feed(self, r: InferRequest) -> List[int]:
        """The decode feed for one request: last accepted token plus up to
        k-1 bigram drafts, never past its max_new budget."""
        feed = [r.generated[-1]]
        k = min(self.engine.spec_k, r.max_new - len(r.generated))
        cur = feed[0]
        while len(feed) < k:
            nxt = r.draft.get(cur)
            if nxt is None:
                break
            feed.append(nxt)
            cur = nxt
        r.spec_fed = len(feed)
        return feed

    def _build_plan(self) -> Optional[tuple]:
        """Under the lock: evict, admit, snapshot one step. Returns
        (plan, prefills, decodes, releases) or None when idle."""
        self._evict_expired(monotonic())
        budget = self.engine.prefill_chunk or None   # None = unbounded
        prefills: List[InferRequest] = []
        # continuing chunked prefills first (FIFO by admission)
        for r in self._prefilling:
            if budget is not None and budget <= 0:
                break
            remaining = len(r.prompt) - r.pf_done
            take = remaining if budget is None else min(remaining, budget)
            if take <= 0:
                continue
            if budget is not None:
                budget -= take
            r.pf_chunk = take
            r.tag = PREFILL_TAG_BASE + next(self._stream) % 4096
            prefills.append(r)
        # fresh admissions under slot + KV + prefill-budget pressure
        while (self._pending
               and (len(self._active) + len(self._prefilling)
                    < self.max_batch)
               and (budget is None or budget > 0)):
            head = self._pending[0]
            if not self.engine.can_admit(head.slot, head.kv_need):
                break                     # KV backpressure: FIFO holds
            self._pending.popleft()
            self.engine.reserve(head.slot, head.kv_need)
            hit = self.engine.kv_prefix_acquire(head.rid, head.slot,
                                               head.prompt)
            head.pf_done = hit
            self.counters["prefix_hit_tokens"] += hit
            self.counters["prefix_miss_tokens"] += len(head.prompt) - hit
            if perfvars.enabled():
                perfvars.note_infer(kv_prefix_hit_tokens=hit,
                                    kv_prefix_miss_tokens=(len(head.prompt)
                                                           - hit))
            remaining = len(head.prompt) - hit
            take = remaining if budget is None else min(remaining, budget)
            if budget is not None:
                budget -= take
            head.pf_chunk = take
            head.tag = PREFILL_TAG_BASE + next(self._stream) % 4096
            head.state = "running"
            self.counters["admitted"] += 1
            self._prefilling.append(head)
            prefills.append(head)
        decodes = list(self._active)
        releases = self._releases
        self._releases = []
        if not prefills and not decodes and not releases:
            if not self._prefilling:
                self._wake.clear()
            return None
        share = self.engine.prefix_share
        plan = StepPlan(
            next(self._seq),
            [Prefill(r.rid, r.slot,
                     r.prompt[r.pf_done:r.pf_done + r.pf_chunk], r.tag,
                     pos0=r.pf_done,
                     last=(r.pf_done + r.pf_chunk == len(r.prompt)),
                     register=(r.prompt if share else None))
             for r in prefills],
            [Decode(r.rid, r.slot, self._draft_feed(r), r.pos)
             for r in decodes],
            [r.rid for r in releases])
        plan.trace = next((r.trace for r in prefills + decodes
                           if r.trace is not None), None)
        return plan, prefills, decodes, releases

    def pause(self, timeout: float = 30.0) -> bool:
        """Park the batching loop at a step boundary (the elastic rebind
        quiesce): requests keep queueing and SLO deadlines keep ticking —
        a request whose deadline passes while paused is evicted at resume —
        but nothing touches the engine until :meth:`resume`. Returns True
        once the loop is parked (no step mid-flight)."""
        self._paused.set()
        self._wake.set()
        return self._boundary.wait(timeout)

    def resume(self) -> None:
        self._paused.clear()
        self._boundary.clear()
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=0.2)
            if self._stop.is_set():
                return
            if self._paused.is_set():
                self._boundary.set()
                time.sleep(0.01)
                continue
            with self._lock:
                built = self._build_plan()
            if built is None:
                continue
            plan, prefills, decodes, releases = built
            t0 = time.perf_counter_ns()
            try:
                results = self.engine.run_step(plan)
            except BaseException as e:      # noqa: BLE001 - engine is down
                self._dead = e
                with self._lock:
                    doomed = (prefills + decodes + list(self._pending)
                              + [r for r in self._prefilling
                                 if r not in prefills])
                    self._pending.clear()
                    self._prefilling.clear()
                    self._active.clear()
                for r in doomed:
                    r.fail(e if isinstance(e, MPIError) else
                           MPIError(f"inference step failed: {e!r}",
                                    code=_ec.ERR_OTHER))
                return
            step_ns = time.perf_counter_ns() - t0
            self._book_step(plan, prefills, decodes, releases, results,
                            step_ns)

    def _book_step(self, plan, prefills, decodes, releases, results,
                   step_ns) -> None:
        emitted = 0
        drafted = accepted = 0
        now = monotonic()
        with self._lock:
            for r in releases:
                self.engine.unreserve(r.slot, r.kv_need)
            for r in prefills:
                r.pf_done += r.pf_chunk
                r.pf_chunk = 0
            for r in prefills + decodes:
                if r.state != "running":
                    continue              # cancelled while the step ran
                if r in prefills:
                    if r.pf_done < len(r.prompt):
                        continue          # chunked prefill still going
                    r.pos = len(r.prompt)  # first decode feeds at this pos
                toks = results.get(r.rid)
                if not toks:
                    continue
                if r in prefills:
                    self._prefilling.remove(r)
                    self._active.append(r)
                else:
                    toks = toks[:r.max_new - len(r.generated)]
                    r.pos += len(toks)
                    self.counters["spec_drafted"] += r.spec_fed - 1
                    self.counters["spec_accepted"] += len(toks) - 1
                    drafted += r.spec_fed - 1
                    accepted += len(toks) - 1
                # extend the bigram draft table along the accepted stream
                prev = (r.generated[-1] if r.generated
                        else (r.prompt[-1] if r.prompt else None))
                for t in toks:
                    if prev is not None:
                        r.draft[prev] = t
                    prev = t
                r.generated.extend(toks)
                emitted += len(toks)
                r.out.put(("tok", list(toks)))
                if len(r.generated) >= r.max_new:
                    self._active.remove(r)
                    self._releases.append(r)
                    hit = r.deadline is None or now <= r.deadline
                    self.counters["slo_hits" if hit else "slo_misses"] += 1
                    self.counters["completed"] += 1
                    if perfvars.enabled():
                        perfvars.note_infer(
                            **{"slo_hits" if hit else "slo_misses": 1})
                    r.finish({"total_tokens": len(r.generated),
                              "slo_hit": hit,
                              "latency_ms": round((now - r.submitted) * 1e3,
                                                  3)})
            self.counters["steps"] += 1
            self.counters["step_ns"] += step_ns
            self.counters["tokens"] += emitted
            self.counters["batch_slots"] += len(prefills) + len(decodes)
            self.counters["prefill_tokens"] += sum(len(p.tokens)
                                                   for p in plan.prefills)
            if self._pending or self._releases or self._prefilling:
                self._wake.set()
        if perfvars.enabled():
            perfvars.note_infer(steps=1, step_ns=step_ns, tokens=emitted,
                                batch_slots=len(prefills) + len(decodes),
                                prefills=len(prefills),
                                spec_drafted=drafted, spec_accepted=accepted)
            kv = self.engine.kv_stats()
            perfvars.set_infer_gauges(
                max_batch=self.max_batch,
                spec_k=self.engine.spec_k,
                kv_blocks_per_rank=kv["blocks_per_rank"],
                kv_in_use_max=kv["in_use_max"],
                kv_peak_in_use_max=kv["peak_in_use_max"],
                kv_alloc_failures=kv["alloc_failures"],
                kv_shared_blocks_max=kv["shared_blocks_max"],
                kv_prefix_entries_max=kv["prefix_entries_max"],
                kv_cow_forks=kv["cow_forks"])

    # -- reporting -----------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            c = dict(self.counters)
            pending = len(self._pending)
            active = len(self._active) + len(self._prefilling)
        finished = c["slo_hits"] + c["slo_misses"]
        decode_s = c["step_ns"] / 1e9
        rounds = self.engine.moe_rounds
        probed = c["prefix_hit_tokens"] + c["prefix_miss_tokens"]
        return {
            "max_batch": self.max_batch, "slo_ms": self.slo_ms,
            "pending": pending, "active": active,
            "paused": self._paused.is_set(), **c,
            "tokens_per_s": (round(c["tokens"] / decode_s, 3)
                             if decode_s > 0 else None),
            "batch_occupancy": (round(c["batch_slots"]
                                      / (c["steps"] * self.max_batch), 4)
                                if c["steps"] else None),
            "slo_hit_rate": (round(c["slo_hits"] / finished, 4)
                             if finished else None),
            "decode": {
                "vectorized": self.engine.vectorized,
                "spec_k": self.engine.spec_k,
                "prefill_chunk": self.engine.prefill_chunk,
                "moe_rounds": rounds,
                "rounds_per_token": (round(rounds / c["tokens"], 4)
                                     if c["tokens"] else None),
                "drafted": c["spec_drafted"],
                "accepted": c["spec_accepted"],
                "accept_rate": (round(c["spec_accepted"]
                                      / c["spec_drafted"], 4)
                                if c["spec_drafted"] else None),
            },
            "kv": {
                **self.engine.kv_stats(),
                "prefix_share": self.engine.prefix_share,
                "prefix_hit_tokens": c["prefix_hit_tokens"],
                "prefix_miss_tokens": c["prefix_miss_tokens"],
                "prefix_hit_rate": (round(c["prefix_hit_tokens"] / probed, 4)
                                    if probed else None),
            },
        }
