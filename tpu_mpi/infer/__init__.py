"""Continuous-batching expert-parallel MoE inference on the serve tier.

The broker (``tpurun --serve --infer``) owns one :class:`InferEngine`
(per-rank model shards, paged KV caches, the step executor over the warm
pool) and one :class:`InferScheduler` (admission, SLO eviction,
continuous batching). Clients stream tokens through
``ClientSession.generate`` — see docs/serving.md, "Inference engine".
"""

from .engine import Decode, InferEngine, Prefill, StepPlan
from .kvcache import (PagedKVCache, PartitionStreamReader,
                      PartitionStreamWriter)
from .scheduler import InferRequest, InferScheduler

__all__ = ["Decode", "InferEngine", "InferRequest", "InferScheduler",
           "PagedKVCache", "PartitionStreamReader", "PartitionStreamWriter",
           "Prefill", "StepPlan"]
