"""Expert-parallel MoE inference executor on the serve tier's warm pool.

Topology: the pool's R ranks split into 2 pipeline stages of ``ep = R//2``
experts each — stage 0 owns layers ``[0, L/2)`` on ranks ``[0, ep)``,
stage 1 owns ``[L/2, L)`` on ranks ``[ep, R)``; rank ``s`` pairs with rank
``s + ep``. Every admitted request has a *home slot* ``s``: its KV chains
and attention run on the pair ``(s, s + ep)``, while its MoE FFN tokens
route to whichever expert rank the gate picks via
:func:`tpu_mpi.parallel.ep.moe_host_dispatch_combine` — two Alltoallv
rendezvous plus a count Alltoall per layer round, all passing through
the algorithm-selection layer and the online bandit's decision point.

Decode fast path (docs/serving.md "Decode fast path"):

- **Vectorized dispatch** (``TPU_MPI_INFER_VECTORIZED``, default on):
  every co-batched prefill advances partition-p-for-everyone per round,
  so one step makes ONE batched Alltoallv dispatch + one combine per
  layer round with all requests' rows concatenated and per-peer counts
  taken from the whole batch — instead of one round per request per
  partition. Decode rows were already co-batched per layer.
- **Speculative multi-token decode** (``TPU_MPI_INFER_SPEC_K``): a
  :class:`Decode` feeds up to k token rows per request (last accepted
  token + k-1 drafted); stage 1 accepts the longest prefix where each
  drafted token equals the greedy output one row earlier, so every
  accepted token is bitwise the k=1 token. Rejected rows' KV is rolled
  back by the next plan's authoritative ``pos`` (no extra rendezvous).
- **KV prefix sharing** (``TPU_MPI_KV_PREFIX_SHARE``): admission adopts
  registered prompt-prefix blocks (:meth:`kv_prefix_acquire`) so prefill
  only computes the divergent suffix.

Determinism contract (the scheduler-order-independence acceptance): every
batch-size-dependent reduction is forbidden. Attention is computed one
token row at a time against that session's own KV; experts apply row-wise
inside the dispatcher; the MoE capacity always covers a sender's worst
case, so no token is ever dropped by co-batching. A request's token
sequence is a function of its prompt and the model alone — which is the
left-fold composition argument for why the batched dispatch, the
speculative verify pass, and an adopted shared prefix all reproduce the
row-loop k=1 private-KV stream bitwise.

Rank-uniformity contract: all R ranks execute the SAME :class:`StepPlan`,
so every rank makes the identical sequence of collective calls per step —
non-home ranks contribute zero token rows, chunk boundaries and prefix
hit lengths ride in the plan. That is what lets prefill and decode
co-batch freely without collective-order divergence (T201/T202).

Prefill streams stage 0 -> stage 1 through the partitioned-op machinery
(:class:`~tpu_mpi.infer.kvcache.PartitionStreamWriter` /
``PartitionStreamReader``): stage 1 attends over prompt block p while
stage 0 is still computing block p+1. Decode hidden states cross stages
as one plain Send/Recv per step, counts known from the shared plan.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import config
from .. import locksmith
from .. import perfvars
from ..error import MPIError
from .. import error as _ec
from .kvcache import PagedKVCache, PartitionStreamReader, PartitionStreamWriter

PREFILL_TAG_BASE = 0x5A00     # + stream ordinal % 4096 (partitioned tags)
DECODE_TAG_BASE = 0x4D00      # + step seq % 4096 (plain Send/Recv)
N_STAGES = 2


class Prefill:
    """One prompt chunk of one request: ``tokens`` starting at global
    position ``pos0`` (> 0 after a prefix-share hit or an earlier chunk);
    ``last`` marks the chunk that produces the first sampled token;
    ``register`` carries the full prompt for prefix-registry publication
    on the home pair (None = sharing off)."""

    __slots__ = ("rid", "slot", "tokens", "tag", "pos0", "last", "register")

    def __init__(self, rid: int, slot: int, tokens: List[int], tag: int,
                 pos0: int = 0, last: bool = True,
                 register: Optional[List[int]] = None):
        self.rid, self.slot, self.tokens, self.tag = rid, slot, tokens, tag
        self.pos0, self.last, self.register = int(pos0), bool(last), register


class Decode:
    """One decode feed of one request: ``tokens[0]`` is the last accepted
    token, the rest are speculative drafts; ``pos`` is the global position
    of ``tokens[0]`` AND the authoritative KV length — every rank rolls
    the session's chains back to ``pos`` before feeding (the speculative
    rejection rollback, no extra rendezvous needed)."""

    __slots__ = ("rid", "slot", "tokens", "pos")

    def __init__(self, rid: int, slot: int, tokens, pos: int):
        self.rid, self.slot, self.pos = rid, slot, pos
        self.tokens = [int(tokens)] if np.isscalar(tokens) else \
            [int(t) for t in tokens]

    @property
    def token(self) -> int:
        return self.tokens[0]


class StepPlan:
    """One continuous-batching step, identical on every rank: prefills
    then decodes (both rid-ordered), plus sessions to release."""

    __slots__ = ("seq", "prefills", "decodes", "releases", "trace")

    def __init__(self, seq: int, prefills: List[Prefill],
                 decodes: List[Decode], releases: List[int]):
        self.seq = seq
        self.prefills = sorted(prefills, key=lambda p: p.rid)
        self.decodes = sorted(decodes, key=lambda d: d.rid)
        self.releases = sorted(releases)
        # request tracing: the context of one traced request in this batch
        # (the step is shared, so its rank phase spans attribute to it)
        self.trace = None


def _gelu(x: np.ndarray) -> np.ndarray:
    return 0.5 * x * (1.0 + np.tanh(0.7978845608028654
                                    * (x + 0.044715 * x * x * x)))


def _rms_row(x: np.ndarray, scale: np.ndarray) -> np.ndarray:
    return x * (1.0 / np.sqrt(np.mean(x * x) + 1e-6)) * scale


def _softmax_row(x: np.ndarray) -> np.ndarray:
    e = np.exp(x - x.max())
    return e / e.sum()


def _rope_row(x: np.ndarray, pos: int) -> np.ndarray:
    """Rotary embedding of one token's (h, dh) heads at global ``pos``."""
    half = x.shape[-1] // 2
    ang = pos / (10000.0 ** (np.arange(half, dtype=np.float32)
                             / np.float32(half)))
    cos, sin = np.cos(ang), np.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    return np.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)


class InferEngine:
    """The per-rank model shards + KV caches + step executor. Owned by the
    broker; driven one :class:`StepPlan` at a time by the scheduler."""

    def __init__(self, pool, cfg=None, *, seed: int = 0,
                 max_batch: Optional[int] = None,
                 block_tokens: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 vectorized: Optional[bool] = None,
                 spec_k: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_share: Optional[bool] = None):
        from ..models.transformer import TransformerConfig
        nr = pool.nranks
        if nr < 2 or nr % 2:
            raise MPIError(
                f"inference engine needs an even warm pool of >= 2 ranks "
                f"(2 pipeline stages x ep experts), got {nr}",
                code=_ec.ERR_ARG)
        knobs = config.load()
        self.pool = pool
        self.ep = nr // 2
        # world ranks hosting the engine, comm order: stage 0 is ranks[:ep],
        # stage 1 is ranks[ep:]. An elastic resize replaces entries in place
        # (rebind), so all comm-relative addressing (slots, p2p peers) holds.
        self.ranks = tuple(range(nr))
        self.cfg = cfg or TransformerConfig(vocab=64, d_model=32, n_heads=2,
                                            n_layers=2, d_ff=64, max_seq=128)
        if self.cfg.n_layers % N_STAGES:
            raise MPIError(f"n_layers={self.cfg.n_layers} must split over "
                           f"{N_STAGES} pipeline stages", code=_ec.ERR_ARG)
        self.layers_local = self.cfg.n_layers // N_STAGES
        self.seed = int(seed)
        self.max_batch = max(1, int(knobs.infer_max_batch
                                    if max_batch is None else max_batch))
        self.block_tokens = max(1, int(knobs.kv_block_tokens
                                       if block_tokens is None
                                       else block_tokens))
        self.vectorized = bool(knobs.infer_vectorized
                               if vectorized is None else vectorized)
        self.spec_k = max(1, int(knobs.infer_spec_k
                                 if spec_k is None else spec_k))
        self.prefill_chunk = max(0, int(knobs.infer_prefill_chunk
                                        if prefill_chunk is None
                                        else prefill_chunk))
        self.prefix_share = bool(knobs.kv_prefix_share
                                 if prefix_share is None else prefix_share)
        if kv_blocks is None:
            per_sess = self.layers_local * math.ceil(self.cfg.max_seq
                                                     / self.block_tokens)
            kv_blocks = self.max_batch * per_sess
        self.kv_blocks = int(kv_blocks)
        self._state: Dict[int, dict] = {}
        self._reserved = [0] * self.ep
        self._resv_lock = locksmith.make_lock("infer.reservations")
        self.moe_rounds = 0           # dispatch/combine rounds, both stages
        self._rounds_lock = locksmith.make_lock("infer.rounds")
        self.wcomm = None
        self.ep_comms = (None, None)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        """Build engine comms and per-rank shards; the pool must be warm."""
        import jax
        from ..comm import Comm
        from ..models.transformer import (transformer_pp_moe_host_params,
                                          transformer_pp_moe_init)
        ctx = self.pool.ctx
        self.wcomm = Comm(self.ranks, ctx.alloc_cid(), ctx=ctx,
                          name="infer-world")
        self.ep_comms = (
            Comm(self.ranks[:self.ep], ctx.alloc_cid(), ctx=ctx,
                 name="infer-ep0"),
            Comm(self.ranks[self.ep:], ctx.alloc_cid(), ctx=ctx,
                 name="infer-ep1"))
        params = transformer_pp_moe_init(jax.random.PRNGKey(self.seed),
                                         self.cfg, self.ep)
        for i, r in enumerate(self.ranks):
            stage, slot = (0, i) if i < self.ep else (1, i - self.ep)
            self._state[r] = {
                "stage": stage, "slot": slot,
                "sp": transformer_pp_moe_host_params(
                    params, self.cfg, self.ep, stage, N_STAGES, slot),
                "kv": PagedKVCache(self.kv_blocks, self.block_tokens,
                                   self.cfg.n_heads, self.cfg.head_dim),
            }

    def rebind(self, mapping: dict) -> None:
        """Point the engine at replacement world ranks after an elastic
        resize (``mapping``: dead world rank -> replacement). Group ORDER is
        preserved position-wise, so every comm-relative address — pipeline
        slots, p2p peers, MoE expert indices — is unchanged; only the world
        ranks behind them move. Fresh cids are allocated (the old channels
        span retired ranks and would fault-check forever) and registered
        eagerly so the first post-resize step is scoped to the new group.

        The per-rank shard state moves with the slot: in the thread tier a
        "dead" rank's memory is still addressable (death is a declaration),
        so weights and KV chains survive the move; a process tier would
        re-shard from checkpoint here instead."""
        from ..comm import Comm
        ctx = self.pool.ctx
        self.ranks = tuple(mapping.get(r, r) for r in self.ranks)
        self.wcomm = Comm(self.ranks, ctx.alloc_cid(), ctx=ctx,
                          name="infer-world")
        self.ep_comms = (
            Comm(self.ranks[:self.ep], ctx.alloc_cid(), ctx=ctx,
                 name="infer-ep0"),
            Comm(self.ranks[self.ep:], ctx.alloc_cid(), ctx=ctx,
                 name="infer-ep1"))
        for c in (self.wcomm, *self.ep_comms):
            ctx.channel(c.cid, len(c.group), c.group)
        for old, new in mapping.items():
            if old in self._state:
                self._state[new] = self._state.pop(old)

    # -- admission accounting (scheduler side) -------------------------------
    def kv_demand(self, prompt_len: int, max_new: int) -> int:
        """Blocks one request can touch on each of its home ranks."""
        return self.layers_local * math.ceil((prompt_len + max_new)
                                             / self.block_tokens)

    def can_admit(self, slot: int, need: int) -> bool:
        with self._resv_lock:
            return self._reserved[slot] + need <= self.kv_blocks

    def reserve(self, slot: int, need: int) -> None:
        with self._resv_lock:
            self._reserved[slot] += need

    def unreserve(self, slot: int, need: int) -> None:
        with self._resv_lock:
            self._reserved[slot] = max(0, self._reserved[slot] - need)

    def kv_prefix_acquire(self, rid: int, slot: int,
                          tokens: List[int]) -> int:
        """Adopt the longest registered shared prompt prefix for ``rid``
        on BOTH home ranks of ``slot``; the two caches evict
        independently, so reconcile to the shorter match (truncate keeps
        the plan's ``pos0`` honest on both stages). Returns adopted
        tokens (0 = off/miss)."""
        if not self.prefix_share or len(tokens) < 2:
            return 0
        c0 = self._state[self.ranks[slot]]["kv"]
        c1 = self._state[self.ranks[slot + self.ep]]["kv"]
        h0 = c0.prefix_acquire(rid, tokens)
        h1 = c1.prefix_acquire(rid, tokens)
        h = min(h0, h1)
        if h0 > h:
            c0.truncate(rid, h)
        if h1 > h:
            c1.truncate(rid, h)
        return h

    def kv_stats(self) -> dict:
        caches = [st["kv"].stats() for st in self._state.values()]
        with self._resv_lock:
            reserved = max(self._reserved) if self._reserved else 0
        return {"blocks_per_rank": self.kv_blocks,
                "block_tokens": self.block_tokens,
                "in_use_max": max(c["in_use"] for c in caches),
                "peak_in_use_max": max(c["peak_in_use"] for c in caches),
                "alloc_failures": sum(c["alloc_failures"] for c in caches),
                "reserved_max": reserved,
                "shared_blocks_max": max(c["shared_blocks"] for c in caches),
                "prefix_entries_max": max(c["prefix_entries"]
                                          for c in caches),
                "prefix_evictions": sum(c["prefix_evictions"]
                                        for c in caches),
                "cow_forks": sum(c["cow_forks"] for c in caches)}

    # -- step execution ------------------------------------------------------
    def run_step(self, plan: StepPlan) -> Dict[int, List[int]]:
        """Execute one plan on every pool rank; returns {rid: accepted
        tokens} (one per prefill, up to spec_k per decode). The per-rank
        closures enqueue under the pool's dispatch lock so engine steps
        interleave atomically with tenant collective ops."""
        results: Dict[int, List[int]] = {}
        errs: list = []
        done = threading.Event()
        remaining = [len(self.ranks)]
        lock = threading.Lock()

        def make(rank):
            def run(_r):
                try:
                    if plan.trace is None:
                        out = self._rank_step(rank, plan)
                    else:
                        from .. import tracectx as _tc
                        with _tc.bind(plan.trace):
                            out = self._rank_step(rank, plan)
                    if out:
                        with lock:
                            results.update(out)
                except BaseException as e:      # noqa: BLE001 - reported below
                    errs.append(e)
                finally:
                    with lock:
                        remaining[0] -= 1
                        if remaining[0] == 0:
                            done.set()
            return run

        with self.pool._dispatch_lock:
            for r in self.ranks:
                self.pool._queues[r].put((None, make(r)))
        if not done.wait(timeout=300.0):
            raise MPIError(f"inference step {plan.seq} timed out on the "
                           f"pool", code=_ec.ERR_OTHER)
        if errs:
            err = errs[0]
            if isinstance(err, MPIError):
                raise err
            raise MPIError(f"inference step failed: {err!r}",
                           code=_ec.ERR_OTHER)
        return results

    def _rank_step(self, rank: int, plan: StepPlan) -> Dict[int, List[int]]:
        st = self._state[rank]
        # speculative rollback: the plan's pos is the authoritative chain
        # length — drop any rows a previous verify pass rejected
        for dc in plan.decodes:
            st["kv"].truncate(dc.rid, dc.pos)
        out = (self._stage0_step(st, plan) if st["stage"] == 0
               else self._stage1_step(st, plan))
        for rid in plan.releases:
            st["kv"].close(rid)
        return out

    # -- shared layer math ---------------------------------------------------
    def _attn_row(self, st: dict, rid: int, li: int, x: np.ndarray,
                  pos: int) -> np.ndarray:
        """Attention of ONE token row at global position ``pos`` over the
        session's own KV chain (appending this token first). Row-at-a-time
        on purpose: no reduction ever spans co-batched sessions."""
        sp = st["sp"]
        d, h = self.cfg.d_model, self.cfg.n_heads
        dh = self.cfg.head_dim
        y = _rms_row(x, sp["ln1"][li])
        qkv = y @ sp["w_qkv"][li]
        q = _rope_row(qkv[:d].reshape(h, dh), pos)
        k = _rope_row(qkv[d:2 * d].reshape(h, dh), pos)
        v = qkv[2 * d:].reshape(h, dh)
        st["kv"].append(rid, li, k, v)
        K, V = st["kv"].view(rid, li)                       # (t, h, dh)
        s = np.einsum("hd,thd->ht", q, K) / np.sqrt(np.float32(dh))
        s = s - s.max(axis=1, keepdims=True)
        w = np.exp(s)
        w = w / w.sum(axis=1, keepdims=True)
        o = np.einsum("ht,thd->hd", w, V).reshape(d)
        return x + o @ sp["w_proj"][li]

    def _moe_rows(self, st: dict, comm, li: int, xs: np.ndarray,
                  capacity: int) -> np.ndarray:
        """The MoE FFN half-layer over this rank's ``(k, d)`` rows: gate,
        dispatch to expert ranks, combine, residual. Called by EVERY rank
        of the stage each round (k may be 0) — rank-uniform collectives.
        One call = one batched dispatch + one combine (plus the count
        exchange); ``moe_rounds`` is what rounds/token is measured from."""
        from ..parallel.ep import moe_host_dispatch_combine
        if st["slot"] == 0:
            with self._rounds_lock:
                self.moe_rounds += 1
            if perfvars.enabled():
                perfvars.note_infer(moe_rounds=1)
        sp = st["sp"]
        d = self.cfg.d_model
        k = xs.shape[0]
        if k:
            ys = np.stack([_rms_row(x, sp["ln2"][li]) for x in xs])
            gates = np.stack([_softmax_row(y @ sp["w_gate"][li]) for y in ys])
            eidx = gates.argmax(axis=1)
            psel = gates[np.arange(k), eidx].astype(np.float32)
        else:
            ys = np.zeros((0, d), np.float32)
            eidx = np.zeros(0, np.int64)
            psel = np.zeros(0, np.float32)
        w_in, w_out = sp["w_in"][li], sp["w_out"][li]

        def expert(rows):
            return _gelu(rows @ w_in) @ w_out

        moe = moe_host_dispatch_combine(ys.astype(np.float32), eidx, expert,
                                        comm, capacity=capacity)
        return xs + moe * psel[:, None]

    def _sample(self, st: dict, x: np.ndarray) -> int:
        """Greedy next token from one final hidden row (ties -> lowest
        token id, np.argmax's first-maximum rule)."""
        sp = st["sp"]
        logits = _rms_row(x, sp["ln_f"]) @ sp["embed"].T
        return int(np.argmax(logits))

    # -- prefill bodies ------------------------------------------------------
    def _prefill_rows0(self, st: dict, plan: StepPlan) -> int:
        """Row-loop baseline (``infer_vectorized`` off): each prefill's
        partitions make their own MoE rounds, one request at a time."""
        cfg, B, slot = self.cfg, self.block_tokens, st["slot"]
        sp, L0 = st["sp"], self.layers_local
        serial_ns = 0
        for pf in plan.prefills:
            tlen = len(pf.tokens)
            nparts = math.ceil(tlen / B)
            mine = pf.slot == slot
            writer = (PartitionStreamWriter(nparts, B, cfg.d_model,
                                            self.ep + slot, pf.tag,
                                            self.wcomm)
                      if mine else None)
            for p in range(nparts):
                lo, hi = p * B, min((p + 1) * B, tlen)
                t0 = time.perf_counter_ns()
                if mine:
                    xs = np.stack([sp["embed"][t].copy()
                                   for t in pf.tokens[lo:hi]])
                else:
                    xs = np.zeros((0, cfg.d_model), np.float32)
                for li in range(L0):
                    for j in range(xs.shape[0]):
                        xs[j] = self._attn_row(st, pf.rid, li, xs[j],
                                               pf.pos0 + lo + j)
                    xs = self._moe_rows(st, self.ep_comms[0], li, xs, B)
                if mine:
                    writer.publish(p, xs)
                    serial_ns += time.perf_counter_ns() - t0
            if writer is not None:
                writer.finish()
                if pf.last and pf.register is not None:
                    st["kv"].register_prefix(pf.rid, pf.register)
        return serial_ns

    def _prefill_vec0(self, st: dict, plan: StepPlan) -> int:
        """Vectorized: every prefill advances partition p together — one
        batched dispatch + combine per (round, layer) for the whole
        co-batch, per-peer counts from all requests' rows at once. Each
        request's rows still stream out the moment its own partition is
        computed, so the cross-stage overlap is untouched."""
        cfg, B, slot = self.cfg, self.block_tokens, st["slot"]
        sp, L0 = st["sp"], self.layers_local
        serial_ns = 0
        live = []
        for pf in plan.prefills:
            nparts = math.ceil(len(pf.tokens) / B)
            mine = pf.slot == slot
            writer = (PartitionStreamWriter(nparts, B, cfg.d_model,
                                            self.ep + slot, pf.tag,
                                            self.wcomm)
                      if mine else None)
            live.append((pf, nparts, mine, writer))
        for p in range(max((e[1] for e in live), default=0)):
            active = [e for e in live if p < e[1]]
            segs, cap = [], 0
            for pf, _, mine, _ in active:
                lo, hi = p * B, min((p + 1) * B, len(pf.tokens))
                cap += hi - lo
                xs = (np.stack([sp["embed"][t].copy()
                                for t in pf.tokens[lo:hi]]) if mine
                      else np.zeros((0, cfg.d_model), np.float32))
                segs.append([pf, lo, xs])
            t0 = time.perf_counter_ns()
            for li in range(L0):
                for seg in segs:
                    pf, lo, xs = seg
                    for j in range(xs.shape[0]):
                        xs[j] = self._attn_row(st, pf.rid, li, xs[j],
                                               pf.pos0 + lo + j)
                cat = (np.concatenate([s[2] for s in segs]) if segs
                       else np.zeros((0, cfg.d_model), np.float32))
                cat = self._moe_rows(st, self.ep_comms[0], li, cat, cap)
                o = 0
                for seg in segs:
                    n = seg[2].shape[0]
                    seg[2] = cat[o:o + n]
                    o += n
            published = False
            for (pf, _, mine, writer), seg in zip(active, segs):
                if mine:
                    writer.publish(p, seg[2])
                    published = True
            if published:
                serial_ns += time.perf_counter_ns() - t0
        for pf, _, mine, writer in live:
            if writer is not None:
                writer.finish()
                if pf.last and pf.register is not None:
                    st["kv"].register_prefix(pf.rid, pf.register)
        return serial_ns

    def _prefill_rows1(self, st: dict, plan: StepPlan,
                       results: Dict[int, List[int]]) -> int:
        cfg, B, slot = self.cfg, self.block_tokens, st["slot"]
        L1 = self.layers_local
        pwait_ns = 0
        for pf in plan.prefills:
            tlen = len(pf.tokens)
            nparts = math.ceil(tlen / B)
            mine = pf.slot == slot
            reader = (PartitionStreamReader(nparts, B, cfg.d_model, slot,
                                            pf.tag, self.wcomm)
                      if mine else None)
            last = None
            for p in range(nparts):
                lo, hi = p * B, min((p + 1) * B, tlen)
                if mine:
                    xs = np.ascontiguousarray(
                        reader.take(p)[:hi - lo]).astype(np.float32)
                else:
                    xs = np.zeros((0, cfg.d_model), np.float32)
                for li in range(L1):
                    for j in range(xs.shape[0]):
                        xs[j] = self._attn_row(st, pf.rid, li, xs[j],
                                               pf.pos0 + lo + j)
                    xs = self._moe_rows(st, self.ep_comms[1], li, xs, B)
                if mine and hi == tlen:
                    last = xs[-1]
            if reader is not None:
                reader.finish()
                pwait_ns += reader.wait_ns
                if pf.last and pf.register is not None:
                    st["kv"].register_prefix(pf.rid, pf.register)
                if pf.last:
                    results[pf.rid] = [self._sample(st, last)]
        return pwait_ns

    def _prefill_vec1(self, st: dict, plan: StepPlan,
                      results: Dict[int, List[int]]) -> int:
        cfg, B, slot = self.cfg, self.block_tokens, st["slot"]
        L1 = self.layers_local
        pwait_ns = 0
        live, lasts = [], {}
        for pf in plan.prefills:
            nparts = math.ceil(len(pf.tokens) / B)
            mine = pf.slot == slot
            reader = (PartitionStreamReader(nparts, B, cfg.d_model, slot,
                                            pf.tag, self.wcomm)
                      if mine else None)
            live.append((pf, nparts, mine, reader))
        for p in range(max((e[1] for e in live), default=0)):
            active = [e for e in live if p < e[1]]
            segs, cap = [], 0
            for pf, _, mine, reader in active:
                lo, hi = p * B, min((p + 1) * B, len(pf.tokens))
                cap += hi - lo
                xs = (np.ascontiguousarray(
                    reader.take(p)[:hi - lo]).astype(np.float32) if mine
                    else np.zeros((0, cfg.d_model), np.float32))
                segs.append([pf, lo, hi, xs])
            for li in range(L1):
                for seg in segs:
                    pf, lo, _, xs = seg
                    for j in range(xs.shape[0]):
                        xs[j] = self._attn_row(st, pf.rid, li, xs[j],
                                               pf.pos0 + lo + j)
                cat = (np.concatenate([s[3] for s in segs]) if segs
                       else np.zeros((0, cfg.d_model), np.float32))
                cat = self._moe_rows(st, self.ep_comms[1], li, cat, cap)
                o = 0
                for seg in segs:
                    n = seg[3].shape[0]
                    seg[3] = cat[o:o + n]
                    o += n
            for (pf, _, mine, _), seg in zip(active, segs):
                if mine and seg[2] == len(pf.tokens):
                    lasts[pf.rid] = np.array(seg[3][-1])
        for pf, _, mine, reader in live:
            if reader is not None:
                reader.finish()
                pwait_ns += reader.wait_ns
                if pf.last and pf.register is not None:
                    st["kv"].register_prefix(pf.rid, pf.register)
                if pf.last:
                    results[pf.rid] = [self._sample(st, lasts[pf.rid])]
        return pwait_ns

    # -- stage bodies --------------------------------------------------------
    def _decode_cap(self, plan: StepPlan) -> int:
        """Per-expert routing capacity for the decode dispatch — plan-wide
        row total, so no sender can ever overflow it (rank-uniform)."""
        return max(self.max_batch,
                   sum(len(dc.tokens) for dc in plan.decodes))

    def _stage0_step(self, st: dict, plan: StepPlan) -> Dict[int, List[int]]:
        cfg, slot = self.cfg, st["slot"]
        sp, L0 = st["sp"], self.layers_local
        serial_ns = (self._prefill_vec0(st, plan) if self.vectorized
                     else self._prefill_rows0(st, plan))
        mine_dec = [dc for dc in plan.decodes if dc.slot == slot]
        rows = [(dc, j) for dc in mine_dec for j in range(len(dc.tokens))]
        xs = (np.stack([sp["embed"][t].copy()
                        for dc in mine_dec for t in dc.tokens])
              if rows else np.zeros((0, cfg.d_model), np.float32))
        cap = self._decode_cap(plan)
        for li in range(L0):
            for i, (dc, j) in enumerate(rows):
                xs[i] = self._attn_row(st, dc.rid, li, xs[i], dc.pos + j)
            xs = self._moe_rows(st, self.ep_comms[0], li, xs, cap)
        if rows:
            from .. import pointtopoint as p2p
            p2p.Send(np.ascontiguousarray(xs, dtype=np.float32),
                     self.ep + slot, DECODE_TAG_BASE + plan.seq % 4096,
                     self.wcomm)
        if serial_ns and perfvars.enabled():
            perfvars.note_infer(stage_serial_ns=serial_ns)
        return {}

    def _stage1_step(self, st: dict, plan: StepPlan) -> Dict[int, List[int]]:
        cfg, slot = self.cfg, st["slot"]
        L1 = self.layers_local
        results: Dict[int, List[int]] = {}
        pwait_ns = (self._prefill_vec1(st, plan, results) if self.vectorized
                    else self._prefill_rows1(st, plan, results))
        mine_dec = [dc for dc in plan.decodes if dc.slot == slot]
        rows = [(dc, j) for dc in mine_dec for j in range(len(dc.tokens))]
        if rows:
            from .. import pointtopoint as p2p
            xs = np.zeros((len(rows), cfg.d_model), np.float32)
            p2p.Recv(xs, slot, DECODE_TAG_BASE + plan.seq % 4096, self.wcomm)
        else:
            xs = np.zeros((0, cfg.d_model), np.float32)
        cap = self._decode_cap(plan)
        for li in range(L1):
            for i, (dc, j) in enumerate(rows):
                xs[i] = self._attn_row(st, dc.rid, li, xs[i], dc.pos + j)
            xs = self._moe_rows(st, self.ep_comms[1], li, xs, cap)
        # speculative acceptance: row i's greedy output is valid iff every
        # drafted token before it matched the greedy output one row
        # earlier — so each accepted token is bitwise the k=1 token
        o = 0
        for dc in mine_dec:
            kk = len(dc.tokens)
            outs = [self._sample(st, xs[o + i]) for i in range(kk)]
            m = 1
            while m < kk and dc.tokens[m] == outs[m - 1]:
                m += 1
            results[dc.rid] = outs[:m]
            o += kk
        if pwait_ns and perfvars.enabled():
            perfvars.note_infer(pwait_ns=pwait_ns)
        return results
