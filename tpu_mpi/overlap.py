"""Host-path overlap engine: chunk schedules, persistent collective plans,
and in-flight progress state (ISSUE-3 tentpole).

Three coordinated pieces, shared by the thread tier (``_runtime
.CollectiveChannel``), the multi-process tier (``backend.ProcChannel``'s
chunked star) and the nonblocking machinery (``collective._nb_submit``):

- :class:`ChunkSchedule` — how a bulk payload splits into K pipeline chunks
  (``config.pipeline_min_bytes`` / ``config.pipeline_chunks``, the
  ``shm_min_bytes`` knob pattern). Chunking is only ever applied to
  elementwise rank-order folds, where it is *chunk-separable*: the pipelined
  result is bitwise-identical to the monolithic one.
- :class:`PlanCache` / :class:`CollectivePlan` — repeated same-shape
  collectives (the training-loop case) resolve their op, combine closure,
  opname tag, trace signature and chunk schedule ONCE and reuse the plan;
  keyed on (comm, op, dtype, shape, flavor) and invalidated by
  ``Comm.free`` and by config reloads (``config.GENERATION``).
- :class:`ChunkProgress` — per-request in-flight chunk state that the
  progress threads (the per-comm nonblocking worker; the multi-process
  drainer feeding it) advance while the rank thread is in user code, and
  that ``Wait``/``Test`` join instead of executing the whole op.

:class:`PersistentCollRequest` is the persistent-collective handle behind
``Allreduce_init``-style APIs (MPI-4 persistent collectives), mirroring the
persistent P2P machinery (:class:`tpu_mpi.pointtopoint.Prequest`).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from . import error as _ec
from .error import MPIError


class ChunkSchedule:
    """A bulk payload's split into pipeline chunks.

    ``bounds`` is a list of flat-element ``(lo, hi)`` half-open ranges
    covering ``[0, count)`` in order. Every chunk has ``base`` elements and
    the LAST chunk absorbs the remainder (``count % nchunks``), so uneven
    payloads never produce an empty chunk and never reorder elements —
    chunked rank-order folds stay bitwise-equal to monolithic ones.
    """

    __slots__ = ("count", "nchunks", "bounds")

    def __init__(self, count: int, nchunks: int):
        count, nchunks = int(count), int(nchunks)
        nchunks = max(1, min(nchunks, count))
        base = count // nchunks
        self.count = count
        self.nchunks = nchunks
        self.bounds = [(i * base, (i + 1) * base if i < nchunks - 1 else count)
                       for i in range(nchunks)]

    @classmethod
    def maybe(cls, count: int, itemsize: int) -> Optional["ChunkSchedule"]:
        """The schedule for a payload, or None when pipelining is off or
        the payload is below ``pipeline_min_bytes`` (monolithic path)."""
        from . import config
        cfg = config.load()
        if cfg.pipeline_min_bytes <= 0 or cfg.pipeline_chunks < 2:
            return None
        if int(count) * int(itemsize) < cfg.pipeline_min_bytes:
            return None
        sched = cls(count, cfg.pipeline_chunks)
        return sched if sched.nchunks > 1 else None

    def __iter__(self):
        return iter(self.bounds)

    def __len__(self) -> int:
        return self.nchunks

    def __repr__(self) -> str:
        return f"ChunkSchedule({self.count} elems x {self.nchunks} chunks)"


class CollectivePlan:
    """Everything a repeated same-signature collective can pre-resolve:
    the resolved :class:`~tpu_mpi.operators.Op`, the rendezvous combine
    closure, the opname tag, the trace-verifier signature, the algorithm
    hint for the multi-process tier (carrying the ``tune.select`` decision,
    so the algorithm is resolved once per signature and invalidated with
    the plan), and the chunk schedule."""

    __slots__ = ("opname", "op", "combine", "sig", "hint", "schedule",
                 "generation", "algo")

    def __init__(self, opname: str, op: Any, combine: Callable, sig: dict,
                 hint: Any, schedule: Optional[ChunkSchedule],
                 generation: int, algo: str = "star"):
        self.opname = opname
        self.op = op
        self.combine = combine
        self.sig = sig
        self.hint = hint
        self.schedule = schedule
        self.generation = generation
        self.algo = algo


class PlanCache:
    """Bounded LRU of :class:`CollectivePlan` keyed on the collective's
    full call signature: (cid, family, op identity, count, dtype, array
    kind, flavor). Entries from a stale ``config.GENERATION`` miss (the
    pipeline knobs feed the schedule), and :meth:`invalidate` drops a
    freed communicator's plans. Unhashable keys (an unhashable custom op)
    simply never cache."""

    CAP = 128

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: "OrderedDict[Any, CollectivePlan]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Any) -> Optional[CollectivePlan]:
        from . import config
        try:
            hash(key)
        except TypeError:
            return None
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None and plan.generation == config.GENERATION:
                self._plans.move_to_end(key)
                self.hits += 1
                return plan
            if plan is not None:                 # stale config generation
                del self._plans[key]
            self.misses += 1
            return None

    def put(self, key: Any, plan: CollectivePlan) -> None:
        try:
            hash(key)
        except TypeError:
            return
        with self._lock:
            self._plans[key] = plan
            self._plans.move_to_end(key)
            while len(self._plans) > self.CAP:
                self._plans.popitem(last=False)

    def invalidate(self, cid: Any = None) -> None:
        """Drop every plan (no args) or one communicator's plans
        (``Comm.free``)."""
        with self._lock:
            if cid is None:
                self._plans.clear()
                return
            for k in [k for k in self._plans if k[0] == cid]:
                del self._plans[k]

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._plans), "hits": self.hits,
                    "misses": self.misses}


#: The process-wide plan cache. ``Comm.free`` invalidates per-cid; config
#: reloads invalidate by generation.
plans = PlanCache()


class ChunkProgress:
    """In-flight chunk state for one nonblocking collective, advanced by
    whichever progress thread moves the op (the per-comm worker; at a
    multi-process star root, the fold loop fed by the drainer) and read by
    ``Test``/``Wait`` and by benchmarks. ``total`` is 0 until the op's
    chunk schedule is known (monolithic ops never set it)."""

    __slots__ = ("done", "total", "stage")

    def __init__(self):
        self.done = 0
        self.total = 0
        self.stage = "pending"

    def begin(self, total: int, stage: str) -> None:
        self.total = int(total)
        self.done = 0
        self.stage = stage

    def note(self, done: Optional[int] = None) -> None:
        self.done = self.done + 1 if done is None else int(done)

    def __repr__(self) -> str:
        return f"<ChunkProgress {self.stage} {self.done}/{self.total}>"


_progress_tls = threading.local()


def bind_progress(prog: Optional[ChunkProgress]) -> None:
    """Bind the progress record the current thread's collective work should
    advance (set by the nonblocking worker around each op; None clears)."""
    _progress_tls.current = prog


def current_progress() -> Optional[ChunkProgress]:
    return getattr(_progress_tls, "current", None)


def progress_begin(total: int, stage: str) -> Optional[ChunkProgress]:
    prog = current_progress()
    if prog is not None:
        prog.begin(total, stage)
    return prog


def progress_note(prog: Optional[ChunkProgress]) -> None:
    if prog is not None:
        prog.note()


class PersistentCollRequest:
    """Persistent collective request (MPI-4 ``MPI_Allreduce_init`` family),
    mirroring :class:`tpu_mpi.pointtopoint.Prequest`: created INACTIVE with
    the operation's arguments bound (and its plan pre-resolved), armed by
    ``Start``/``Startall``, completed by the whole Wait/Test family, then
    inactive-but-reusable for the next round. Each Start initiates the
    collective on this rank's per-comm worker, so rounds progress in the
    background exactly like the one-shot ``I*`` ops."""

    def __init__(self, make: Callable[[], Any], kind: str, buffer: Any):
        self._make = make           # () -> a live CollRequest
        self._inner = None
        self.kind = kind            # e.g. "pallreduce"
        self.buffer = buffer
        self.status = None
        self.result = None          # allocating flavors: last round's value

    def start(self) -> "PersistentCollRequest":
        if self._inner is not None and self._inner.active:
            raise MPIError("Start on an already-active persistent request",
                           code=_ec.ERR_REQUEST)
        self._inner = self._make()
        return self

    @property
    def active(self) -> bool:
        return self._inner is not None and self._inner.active

    @property
    def progress(self) -> Optional[ChunkProgress]:
        return getattr(self._inner, "progress", None)

    def test(self) -> bool:
        if self._inner is None:
            return True
        done = self._inner.test()
        if done:
            self.result = self._inner.result
        return done

    def wait(self):
        from .pointtopoint import STATUS_EMPTY
        if self._inner is None:
            return self.status or STATUS_EMPTY
        self.status = self._inner.wait()
        self.result = self._inner.result
        self._inner = None          # inactive, ready for the next Start
        return self.status

    def _consume(self):
        from .pointtopoint import STATUS_EMPTY
        if self._inner is None:
            return self.status or STATUS_EMPTY
        self.status = self._inner.wait() if self._inner.active \
            else (self._inner.status or STATUS_EMPTY)
        self.result = self._inner.result
        self._inner = None
        return self.status

    def cancel(self) -> None:
        raise MPIError("nonblocking collectives cannot be cancelled")

    def __repr__(self) -> str:
        return f"<PersistentCollRequest {self.kind} active={self.active}>"
