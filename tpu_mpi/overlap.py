"""Host-path overlap engine: chunk schedules, persistent collective plans,
and in-flight progress state (ISSUE-3 tentpole).

Three coordinated pieces, shared by the thread tier (``_runtime
.CollectiveChannel``), the multi-process tier (``backend.ProcChannel``'s
chunked star) and the nonblocking machinery (``collective._nb_submit``):

- :class:`ChunkSchedule` — how a bulk payload splits into K pipeline chunks
  (``config.pipeline_min_bytes`` / ``config.pipeline_chunks``, the
  ``shm_min_bytes`` knob pattern). Chunking is only ever applied to
  elementwise rank-order folds, where it is *chunk-separable*: the pipelined
  result is bitwise-identical to the monolithic one.
- :class:`PlanCache` / :class:`CollectivePlan` — repeated same-shape
  collectives (the training-loop case) resolve their op, combine closure,
  opname tag, trace signature and chunk schedule ONCE and reuse the plan;
  keyed on (comm, op, dtype, shape, flavor) and invalidated by
  ``Comm.free`` and by config reloads (``config.GENERATION``).
- :class:`ChunkProgress` — per-request in-flight chunk state that the
  progress threads (the per-comm nonblocking worker; the multi-process
  drainer feeding it) advance while the rank thread is in user code, and
  that ``Wait``/``Test`` join instead of executing the whole op.

:class:`PersistentCollRequest` is the persistent-collective handle behind
``Allreduce_init``-style APIs (MPI-4 persistent collectives), mirroring the
persistent P2P machinery (:class:`tpu_mpi.pointtopoint.Prequest`).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Optional

from . import error as _ec
from . import locksmith
from .error import MPIError


class ChunkSchedule:
    """A bulk payload's split into pipeline chunks.

    ``bounds`` is a list of flat-element ``(lo, hi)`` half-open ranges
    covering ``[0, count)`` in order. Every chunk has ``base`` elements and
    the LAST chunk absorbs the remainder (``count % nchunks``), so uneven
    payloads never produce an empty chunk and never reorder elements —
    chunked rank-order folds stay bitwise-equal to monolithic ones.
    """

    __slots__ = ("count", "nchunks", "bounds")

    def __init__(self, count: int, nchunks: int):
        count, nchunks = int(count), int(nchunks)
        nchunks = max(1, min(nchunks, count))
        base = count // nchunks
        self.count = count
        self.nchunks = nchunks
        self.bounds = [(i * base, (i + 1) * base if i < nchunks - 1 else count)
                       for i in range(nchunks)]

    @classmethod
    def maybe(cls, count: int, itemsize: int) -> Optional["ChunkSchedule"]:
        """The schedule for a payload, or None when pipelining is off or
        the payload is below ``pipeline_min_bytes`` (monolithic path)."""
        from . import config
        cfg = config.load()
        if cfg.pipeline_min_bytes <= 0 or cfg.pipeline_chunks < 2:
            return None
        if int(count) * int(itemsize) < cfg.pipeline_min_bytes:
            return None
        sched = cls(count, cfg.pipeline_chunks)
        return sched if sched.nchunks > 1 else None

    def __iter__(self):
        return iter(self.bounds)

    def __len__(self) -> int:
        return self.nchunks

    def __repr__(self) -> str:
        return f"ChunkSchedule({self.count} elems x {self.nchunks} chunks)"


class CollectivePlan:
    """Everything a repeated same-signature collective can pre-resolve:
    the resolved :class:`~tpu_mpi.operators.Op`, the rendezvous combine
    closure, the opname tag, the trace-verifier signature, the algorithm
    hint for the multi-process tier (carrying the ``tune.select`` decision,
    so the algorithm is resolved once per signature and invalidated with
    the plan), and the chunk schedule."""

    __slots__ = ("opname", "op", "combine", "sig", "hint", "schedule",
                 "generation", "algo")

    def __init__(self, opname: str, op: Any, combine: Callable, sig: dict,
                 hint: Any, schedule: Optional[ChunkSchedule],
                 generation: int, algo: str = "star"):
        self.opname = opname
        self.op = op
        self.combine = combine
        self.sig = sig
        self.hint = hint
        self.schedule = schedule
        self.generation = generation
        self.algo = algo


class AutoArmEntry:
    """Auto-arm state of ONE repeated collective signature (ISSUE-11
    tentpole): the consecutive-identical-call streak, the buffer
    identities it was counted against, and — once the streak crosses
    ``config.auto_arm_threshold`` — the bound :class:`PlanRegistration`
    whose ``run_round`` the plain call is promoted onto. Owned by
    :class:`PlanCache`; demotion drops the registration (releasing its
    pinned scratch and any shm slot lease) but keeps counting, so the
    signature re-arms after another full streak."""

    __slots__ = ("key", "streak", "calls", "send", "recv", "reg", "hits",
                 "demotions", "rounds", "results", "ineligible_gen")

    def __init__(self, key: Any):
        self.key = key
        self.streak = 0         # consecutive calls with identical buffers
        self.calls = 0          # every call noted against this signature
        self.send = _NO_BUF     # buffer identities of the current streak
        self.recv = _NO_BUF
        self.reg = None         # live PlanRegistration once armed
        self.hits = 0           # rounds run on the armed fast path
        self.demotions = 0
        self.rounds = 0         # armed-round ordinal (R302 trace model)
        self.results = deque(maxlen=4)   # recent result refs (id keep-alive)
        self.ineligible_gen = None  # registration factory said no (per gen)

    @property
    def armed(self) -> bool:
        return self.reg is not None


_NO_BUF = object()   # "no buffer seen yet" sentinel (None is a real value)


class PlanCache:
    """Bounded LRU of :class:`CollectivePlan` keyed on the collective's
    full call signature: (cid, family, op identity, count, dtype, array
    kind, flavor). Entries from a stale ``config.GENERATION`` miss (the
    pipeline knobs feed the schedule), and :meth:`invalidate` drops a
    freed communicator's plans. Unhashable keys (an unhashable custom op)
    simply never cache. Both tables are LRU-bounded by the
    ``TPU_MPI_PLAN_CACHE_MAX`` pressure guard (variable batch shapes mint
    a new signature per ``(count, dtype)``); evictions are counted and
    reported in the pvar plan-cache block.

    Also owns the **auto-arm table** (ISSUE-11): per-signature
    :class:`AutoArmEntry` records counting repeated identical plain
    collective calls toward transparent promotion onto the registered
    persistent path, plus the aggregate armed/demoted/hit counters that
    ``stats()`` (and ``tpurun --stats`` / the serve broker) report."""

    CAP = 128            # built-in default; TPU_MPI_PLAN_CACHE_MAX overrides
    AUTO_CAP = 32

    def __init__(self):
        self._lock = locksmith.make_lock("overlap.plancache")
        self._plans: "OrderedDict[Any, CollectivePlan]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0              # plans dropped by LRU cap pressure
        self._auto: "OrderedDict[Any, AutoArmEntry]" = OrderedDict()
        self._auto_last: dict = {}      # (cid, rank) -> last signature seen
        self._auto_hot: dict = {}       # (cid, rank) -> front-door record
        self.auto_arms = 0
        self.auto_demotions = 0
        self.auto_hits = 0
        self.auto_evictions = 0         # auto-arm entries dropped by the cap
        self._cap_gen = None            # config.GENERATION the caps reflect
        self._cap = self.CAP
        self._auto_cap = self.AUTO_CAP
        self._reserved = 0              # bucket-aware floor (reserve())
        # prime the knob read now: the first-ever config.load() bumps
        # GENERATION, which must not happen inside a later put() (it would
        # invalidate the very plan being stored)
        with self._lock:
            self._caps()

    def _caps(self) -> tuple:
        """(plan cap, auto-table cap), re-read from config per generation —
        the TPU_MPI_PLAN_CACHE_MAX pressure guard for shape churn. Caller
        holds the lock."""
        from . import config
        if self._cap_gen != config.GENERATION:
            cap = max(8, int(config.load().plan_cache_max))
            self._cap_gen = config.GENERATION
            self._cap = cap
            self._auto_cap = max(8, cap // 4)
        return self._cap, self._auto_cap

    def get(self, key: Any) -> Optional[CollectivePlan]:
        from . import config
        try:
            hash(key)
        except TypeError:
            return None
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None and plan.generation == config.GENERATION:
                self._plans.move_to_end(key)
                self.hits += 1
                return plan
            if plan is not None:                 # stale config generation
                del self._plans[key]
            self.misses += 1
            return None

    def put(self, key: Any, plan: CollectivePlan) -> None:
        try:
            hash(key)
        except TypeError:
            return
        with self._lock:
            cap, _ = self._caps()
            cap = max(cap, self._reserved)
            self._plans[key] = plan
            self._plans.move_to_end(key)
            while len(self._plans) > cap:
                self._plans.popitem(last=False)
                self.evictions += 1

    def reserve(self, n: int) -> int:
        """Bucket-aware arm hint (ISSUE-19): raise the effective LRU
        capacity floor to at least ``n`` plans so a set of persistent
        gradient-bucket plans armed together can never evict itself (or
        be evicted by concurrent shape churn) mid-step. Monotonic — the
        floor only grows; the configured cap still applies when larger.
        Returns the effective floor."""
        with self._lock:
            self._reserved = max(self._reserved, int(n))
            return self._reserved

    # -- auto-arm table (ISSUE-11) ------------------------------------------

    def auto_note(self, key: Any, send: Any, recv: Any) -> \
            Optional[AutoArmEntry]:
        """Advance the identity streak of one signature and return its
        entry. A call with DIFFERENT buffer objects than the previous one
        resets the streak (and demotes a live registration — fresh-array
        loops never arm, object churn demotes loud-free); ``None`` when the
        key is unhashable."""
        try:
            hash(key)
        except TypeError:
            return None
        with self._lock:
            # shape/dtype churn on the same (cid, rank) lane demotes the
            # previously-armed signature: a loop whose operand geometry
            # changed is no longer the loop that armed, and its pinned
            # scratch must not linger
            lane = (key[0], key[1]) if isinstance(key, tuple) \
                and len(key) >= 2 else key
            prev = self._auto_last.get(lane)
            if prev is not None and prev != key:
                pe = self._auto.get(prev)
                if pe is not None:
                    self._auto_demote_locked(pe)
                    pe.streak = 0
            self._auto_last[lane] = key
            e = self._auto.get(key)
            if e is None:
                _, auto_cap = self._caps()
                e = self._auto[key] = AutoArmEntry(key)
                while len(self._auto) > auto_cap:
                    _, old = self._auto.popitem(last=False)
                    self._auto_demote_locked(old)
                    self.auto_evictions += 1
            else:
                self._auto.move_to_end(key)
            if e.send is not send or e.recv is not recv:
                self._auto_demote_locked(e)
                e.streak = 0
                e.send, e.recv = send, recv
                e.ineligible_gen = None
            e.streak += 1
            e.calls += 1
            return e

    def auto_hot_get(self, lane: Any):
        """Front-door record of one (cid, rank) lane, or None. Lock-free:
        a single dict probe under the GIL — the caller re-validates the
        registration (released/generation) before trusting it, so a racing
        demotion at worst costs one fall-through to the full gate."""
        return self._auto_hot.get(lane)

    def auto_hot_set(self, lane: Any, rec: tuple) -> None:
        """Publish the armed front-door record for a lane (the exact
        argument tuple of the call that just ran armed, its entry, and the
        send operand's byte size as an in-place-resize tripwire)."""
        self._auto_hot[lane] = rec

    def auto_bind(self, e: AutoArmEntry, reg: Any) -> None:
        """Attach a freshly-built registration to an entry (arm event)."""
        with self._lock:
            if e.reg is not None:
                self._auto_demote_locked(e)
            e.reg = reg
            e.rounds = 0
            self.auto_arms += 1

    def auto_hit(self, e: AutoArmEntry) -> None:
        with self._lock:
            e.hits += 1
            self.auto_hits += 1

    def auto_demote(self, e: AutoArmEntry) -> None:
        """Drop an entry's registration (trace arming, nonblocking traffic,
        identity churn, config reload, LRU pressure). Counting continues —
        the signature re-arms after another full streak."""
        with self._lock:
            self._auto_demote_locked(e)

    def _auto_demote_locked(self, e: AutoArmEntry) -> None:
        reg, e.reg = e.reg, None
        if reg is None:
            return
        # the front-door record holds strong refs to the armed call's
        # buffers; drop it with the registration so demotion releases them
        if isinstance(e.key, tuple) and len(e.key) >= 2:
            self._auto_hot.pop((e.key[0], e.key[1]), None)
        e.demotions += 1
        self.auto_demotions += 1
        try:
            registry.discard(reg)
        except Exception:
            pass

    def invalidate(self, cid: Any = None) -> None:
        """Drop every plan (no args) or one communicator's plans
        (``Comm.free``). Auto-arm entries of the communicator are demoted
        and dropped too (their registrations release pinned scratch and
        shm slot leases)."""
        with self._lock:
            if cid is None:
                self._plans.clear()
                for e in self._auto.values():
                    self._auto_demote_locked(e)
                self._auto.clear()
                self._auto_last.clear()
                self._auto_hot.clear()
                return
            for k in [k for k in self._plans if k[0] == cid]:
                del self._plans[k]
            for k in [k for k in self._auto if k[0] == cid]:
                self._auto_demote_locked(self._auto.pop(k))
            for lane in [ln for ln in self._auto_last
                         if isinstance(ln, tuple) and ln[0] == cid]:
                del self._auto_last[lane]
            for lane in [ln for ln in self._auto_hot if ln[0] == cid]:
                del self._auto_hot[lane]

    def stats(self) -> dict:
        with self._lock:
            sigs = {}
            for k, e in self._auto.items():
                label = "/".join(str(p) for p in k)
                sigs[label] = {
                    "calls": e.calls, "streak": e.streak,
                    "armed": e.reg is not None, "hits": e.hits,
                    "demotions": e.demotions,
                    "hit_rate": (e.hits / e.calls) if e.calls else 0.0,
                }
            cap, auto_cap = self._caps()
            return {"entries": len(self._plans), "hits": self.hits,
                    "misses": self.misses,
                    "cap": max(cap, self._reserved),
                    "reserved": self._reserved,
                    "evictions": self.evictions,
                    "auto": {"tracked": len(self._auto),
                             "armed": sum(1 for e in self._auto.values()
                                          if e.reg is not None),
                             "arms": self.auto_arms,
                             "demotions": self.auto_demotions,
                             "hits": self.auto_hits,
                             "cap": auto_cap,
                             "evictions": self.auto_evictions,
                             "signatures": sigs}}


#: The process-wide plan cache. ``Comm.free`` invalidates per-cid; config
#: reloads invalidate by generation.
plans = PlanCache()


def hint_buckets(comm, nbuckets: int) -> int:
    """Bucket-aware arm hint from the training tier (docs/training.md):
    before arming a gradient-bucket set on ``comm``, guarantee the plan
    cache holds the whole set — one plan per bucket, doubled for the
    send/recv signature pair a control lane may also arm, plus headroom
    for unrelated concurrent traffic. Returns the effective floor."""
    return plans.reserve(2 * int(nbuckets) + 8)


class ChunkProgress:
    """In-flight chunk state for one nonblocking collective, advanced by
    whichever progress thread moves the op (the per-comm worker; at a
    multi-process star root, the fold loop fed by the drainer) and read by
    ``Test``/``Wait`` and by benchmarks. ``total`` is 0 until the op's
    chunk schedule is known (monolithic ops never set it)."""

    __slots__ = ("done", "total", "stage")

    def __init__(self):
        self.done = 0
        self.total = 0
        self.stage = "pending"

    def begin(self, total: int, stage: str) -> None:
        self.total = int(total)
        self.done = 0
        self.stage = stage

    def note(self, done: Optional[int] = None) -> None:
        self.done = self.done + 1 if done is None else int(done)

    def __repr__(self) -> str:
        return f"<ChunkProgress {self.stage} {self.done}/{self.total}>"


_progress_tls = threading.local()


def bind_progress(prog: Optional[ChunkProgress]) -> None:
    """Bind the progress record the current thread's collective work should
    advance (set by the nonblocking worker around each op; None clears)."""
    _progress_tls.current = prog


def current_progress() -> Optional[ChunkProgress]:
    return getattr(_progress_tls, "current", None)


def progress_begin(total: int, stage: str) -> Optional[ChunkProgress]:
    prog = current_progress()
    if prog is not None:
        prog.begin(total, stage)
    return prog


def progress_note(prog: Optional[ChunkProgress]) -> None:
    if prog is not None:
        prog.note()


class PlanRegistration:
    """Plan-bound registered buffers + the pre-resolved round closure of one
    persistent collective (the ISSUE-6 tentpole). Built once at
    ``Allreduce_init`` by :func:`tpu_mpi.collective._register_allreduce`:
    arguments parsed, wire views pinned, the fold scratch pre-allocated
    (``buffers.register_scratch``), the combine / copy-out pre-bound — a
    Start/Wait round is then one inline rendezvous with zero allocation,
    no plan lookup and no worker hop. Tracked in :data:`registry` so
    ``Comm.free`` releases the pinned buffers and any shm slot lease."""

    __slots__ = ("cid", "generation", "scratch", "wire", "run_round",
                 "shm_release", "released", "knob_on", "_nb_probe",
                 "inplace_optin", "round_parts")

    def __init__(self, cid: int, generation: int, run_round: Callable[[], Any],
                 scratch: tuple = (), wire: Any = None,
                 shm_release: Optional[Callable[[], None]] = None,
                 knob_on: bool = True, nb_probe: Optional[Callable] = None,
                 inplace_optin: bool = False, round_parts: Any = None):
        self.cid = cid
        self.generation = generation
        self.run_round = run_round
        self.scratch = scratch          # pinned fold accumulators (id-stable)
        self.wire = wire                # pre-bound send wire view, if host
        self.shm_release = shm_release
        self.released = False
        self.knob_on = knob_on
        self._nb_probe = nb_probe       # () -> outstanding nb ops on the comm
        self.inplace_optin = inplace_optin
        # batched-submission hook (ISSUE-11): the round's split pieces
        # (channel, rank, contrib, combine, opname, runkw, copyout, …) so a
        # Waitall over several armed rounds can deposit them all through ONE
        # thread-tier rendezvous (CollectiveChannel.run_batch). None on the
        # multi-process tier and for registrations that predate the split.
        self.round_parts = round_parts

    def armable(self) -> bool:
        """Whether a Start may take the fast path right now: the knob is on,
        the run is untraced (traced runs keep the fully-evented legacy
        path), and this comm's nonblocking worker is idle (in-flight ``I*``
        ops own the initiation order)."""
        if self.released or not self.knob_on:
            return False
        from .analyze import events as _ev
        if _ev.enabled():
            return False
        return self._nb_probe is None or self._nb_probe() == 0

    def release(self) -> None:
        """Drop the pinned buffers and any shm slot lease (``Comm.free``)."""
        if self.released:
            return
        self.released = True
        self.scratch = ()
        self.wire = None
        self.round_parts = None
        rel, self.shm_release = self.shm_release, None
        if rel is not None:
            rel()


class BufferRegistry:
    """Process-wide registry of live :class:`PlanRegistration` instances,
    keyed by communicator cid. ``Comm.free`` calls :meth:`release` so plan-
    registered wire buffers and shm segment slots never outlive their
    communicator (the ISSUE-6 leak fix); ``TPU_MPI_STRICT`` asserts the
    lease count actually hit zero."""

    def __init__(self):
        self._lock = locksmith.make_lock("overlap.registrations")
        self._by_cid: dict[Any, list] = {}

    def add(self, reg: PlanRegistration) -> PlanRegistration:
        with self._lock:
            self._by_cid.setdefault(reg.cid, []).append(reg)
        return reg

    def release(self, cid: Any) -> int:
        """Release every registration of one communicator; returns how many
        were released."""
        with self._lock:
            regs = self._by_cid.pop(cid, [])
        for reg in regs:
            reg.release()
        return len(regs)

    def discard(self, reg: PlanRegistration) -> None:
        """Release ONE registration and drop it from the ledger (auto-arm
        demotion — the comm stays alive, only this plan's pinned buffers
        and shm lease go)."""
        with self._lock:
            lst = self._by_cid.get(reg.cid)
            if lst is not None and reg in lst:
                lst.remove(reg)
                if not lst:
                    del self._by_cid[reg.cid]
        reg.release()

    def leased(self, cid: Any = None) -> int:
        """Outstanding shm slot leases (one comm, or all) — the strict-mode
        refcount the ``Comm.free`` assert reads."""
        with self._lock:
            regs = [r for k, rs in self._by_cid.items()
                    if cid is None or k == cid for r in rs]
        return sum(1 for r in regs if r.shm_release is not None
                   and not r.released)

    def stats(self) -> dict:
        with self._lock:
            return {"comms": len(self._by_cid),
                    "registrations": sum(len(v) for v in self._by_cid.values())}


#: Live plan registrations; ``Comm.free`` releases per-cid.
registry = BufferRegistry()


_fast_tls = threading.local()     # .armed: {cid: [PersistentCollRequest]}


def _armed_list(cid: Any) -> list:
    armed = getattr(_fast_tls, "armed", None)
    if armed is None:
        armed = _fast_tls.armed = {}
    lst = armed.get(cid)
    if lst is None:
        lst = armed[cid] = []
    return lst


def demote_fast_armed(cid: Any = None) -> None:
    """Push every fast-armed persistent request on THIS thread (of one comm,
    or of all comms) onto the legacy worker path, in Start order. Called
    before anything else initiates on the same communicator — a blocking
    collective (``collective._ordered_run``), a nonblocking submit
    (``collective._nb_submit``), or a second Start — so initiation order
    stays the program order even though fast-armed rounds defer their
    rendezvous to ``Wait``."""
    armed = getattr(_fast_tls, "armed", None)
    if not armed:
        return
    cids = [cid] if cid is not None else list(armed)
    for c in cids:
        for req in list(armed.get(c, ())):
            req._demote()


def flush_fast_armed(cid: Any, upto: Any = None) -> None:
    """Complete fast-armed rounds of one comm on THIS thread, in Start
    order, stopping after ``upto`` (a :class:`PersistentCollRequest`) or
    draining the whole stack. Runs of 2+ rounds whose registrations carry
    ``round_parts`` go through batched rendezvous submission
    (``CollectiveChannel.run_batch``) — K rounds deposit through ONE
    channel lock acquisition and ONE wakeup (ISSUE-11 tentpole (b)) —
    chunked by ``config.batch_max_ops`` / ``config.batch_max_bytes``.
    Each completed request gets its ``result``/``status`` set exactly as
    an inline fast-armed ``wait`` would."""
    lst = _armed_list(cid)
    if not lst:
        return
    run = []
    for r in lst:
        run.append(r)
        if upto is not None and r is upto:
            break
    from . import config
    cfg = config.load()
    cap = max(int(cfg.batch_max_ops), 1)
    max_bytes = int(cfg.batch_max_bytes)
    i = 0
    while i < len(run):
        group = [run[i]]
        nbytes = int((run[i]._reg.round_parts or {}).get("pv_nbytes") or 0) \
            if run[i]._reg is not None and run[i]._reg.round_parts else 0
        i += 1
        while i < len(run) and len(group) < cap:
            reg = run[i]._reg
            if reg is None or reg.round_parts is None \
                    or (group[0]._reg is None
                        or group[0]._reg.round_parts is None):
                break
            b = int(reg.round_parts.get("pv_nbytes") or 0)
            if max_bytes > 0 and nbytes + b > max_bytes:
                break
            group.append(run[i])
            nbytes += b
            i += 1
        _flush_group(cid, group)


def _flush_group(cid: Any, group: list) -> None:
    from .pointtopoint import STATUS_EMPTY
    lst = _armed_list(cid)
    for r in group:
        r._fast_armed = False
        if r in lst:
            lst.remove(r)
    if len(group) == 1 or any(r._reg is None or r._reg.round_parts is None
                              for r in group):
        # no batch lane: inline rounds in Start order (the pre-batching
        # fast-armed wait), each its own rendezvous
        for r in group:
            r.result = r._reg.run_round()
            r.status = STATUS_EMPTY
            r._trace_complete()
        return
    from . import perfvars as _pv
    parts = [r._reg.round_parts for r in group]
    channel = parts[0]["channel"]
    rank = parts[0]["rank"]
    ops = [(p["contrib"](), p["combine"], p["opname"],
            bool(p["runkw"].get("unlocked_fold"))) for p in parts]
    sc = _pv.op_begin() if _pv.enabled() else None
    try:
        results = channel.run_batch(rank, ops)
        for r, p, res in zip(group, parts, results):
            if sc is None:
                r.result = p["copyout"](res)
            else:
                t0 = _pv.monotonic()
                r.result = p["copyout"](res)
                sc.spans.append(("copy", t0, _pv.monotonic()))
            r.status = STATUS_EMPTY
            r._trace_complete()
    finally:
        _pv.note_batch(cid, len(group))
        if sc is not None:
            p0 = parts[0]
            sig = p0["sig"]
            _pv.op_end(sc, p0["comm"], coll="allreduce",
                       algo=sig.get("algo"), dtype=sig.get("dtype"),
                       nbytes=sum(int(p.get("pv_nbytes") or 0)
                                  for p in parts))


def waitall_flush(reqs) -> None:
    """Batch-complete every fast-armed persistent round in ``reqs``
    (``Waitall``'s ISSUE-11 hook): per comm, flush the armed stack in
    Start order up to the DEEPEST member of ``reqs``, so the whole run
    submits through one rendezvous wakeup regardless of the order the
    caller listed the requests in."""
    by_cid: dict = {}
    for r in reqs:
        if isinstance(r, PersistentCollRequest) and r._fast_armed \
                and r._reg is not None:
            by_cid.setdefault(r._reg.cid, set()).add(id(r))
    for cid, ids in by_cid.items():
        deepest = None
        for r in _armed_list(cid):
            if id(r) in ids:
                deepest = r
        if deepest is not None:
            flush_fast_armed(cid, upto=deepest)


class PersistentCollRequest:
    """Persistent collective request (MPI-4 ``MPI_Allreduce_init`` family),
    mirroring :class:`tpu_mpi.pointtopoint.Prequest`: created INACTIVE with
    the operation's arguments bound (and its plan pre-resolved), armed by
    ``Start``/``Startall``, completed by the whole Wait/Test family, then
    inactive-but-reusable for the next round.

    Two execution lanes. The **registered fast path** (a
    :class:`PlanRegistration` bound via :meth:`bind_registration`, the
    default when the operands are eligible): Start arms the round and Wait
    runs it INLINE on the calling thread against the pre-pinned buffers —
    one rendezvous round trip, zero allocation. The **legacy lane**: each
    Start initiates the collective on this rank's per-comm worker, so
    rounds progress in the background exactly like the one-shot ``I*``
    ops; Test on a fast-armed round demotes to this lane (Test must not
    block)."""

    def __init__(self, make: Callable[[], Any], kind: str, buffer: Any,
                 comm: Any = None):
        self._make = make           # () -> a live CollRequest
        self._inner = None
        self.kind = kind            # e.g. "pallreduce"
        self.buffer = buffer
        self.status = None
        self.result = None          # allocating flavors: last round's value
        self._reg: Optional[PlanRegistration] = None
        self._reg_factory: Optional[Callable[[], Any]] = None
        self._fast_armed = False
        # tracing state (tpu_mpi.analyze): the comm the Start/Wait events
        # record against, rounds started so far, and strong refs to recent
        # round results so R302's invalidation ids stay unrecycled.
        self._comm = comm
        self._round = 0
        self._results: deque = deque(maxlen=4)

    def bind_registration(self, factory: Callable[[], Any]
                          ) -> "PersistentCollRequest":
        """Attach the registered-buffer fast path: ``factory()`` builds a
        :class:`PlanRegistration` (or None when the operands are not
        eligible) and is re-run to rebind buffers after a config-generation
        change."""
        self._reg_factory = factory
        self._reg = factory()
        return self

    @property
    def registration(self) -> Optional[PlanRegistration]:
        """The live registration (None = generic path). Exposed for tests
        and benchmarks asserting id-stable pinned buffers."""
        return self._reg

    def start(self) -> "PersistentCollRequest":
        if self.active:
            raise MPIError("Start on an already-active persistent request",
                           code=_ec.ERR_REQUEST)
        from .analyze import events as _ev
        if _ev.enabled() and self._comm is not None:
            # R302 front end: on the donated fast path, this Start re-donates
            # the 2-slot fold ring entry holding round (k-2)'s result — name
            # that buffer so the race pass can flag reads-after-invalidation.
            inval = None
            for rnd, res in self._results:
                if rnd == self._round - 2:
                    inval = _ev.buf_id(res)
            _ev.record_start(self._comm, self.kind, id(self), self._round,
                             invalidates=inval)
        self._round += 1
        reg = self._reg
        if reg is not None:
            from . import config
            if reg.generation != config.GENERATION \
                    and self._reg_factory is not None:
                # config reload: rebind the registered buffers (the pipeline
                # knobs feed the schedule; the knob itself may have flipped)
                reg = self._reg = self._reg_factory()
        if reg is not None and reg.armable():
            lst = _armed_list(reg.cid)
            if lst:
                # earlier armed rounds on this comm. When every round —
                # theirs and ours — carries the batched-submission parts
                # (thread tier) and the stack is under the batch cap, STACK
                # instead of demoting: Wait/Waitall completes the stack in
                # Start order through one rendezvous wakeup
                # (flush_fast_armed -> CollectiveChannel.run_batch,
                # ISSUE-11). Otherwise demote the earlier armed rounds to
                # the worker (initiation order = Start order); the worker
                # is then busy, so this round goes legacy too.
                from . import config
                cap = int(config.load().batch_max_ops)
                stackable = (cap > 1 and len(lst) < cap
                             and reg.round_parts is not None
                             and all(r._reg is not None
                                     and r._reg.round_parts is not None
                                     for r in lst))
                if not stackable:
                    demote_fast_armed(reg.cid)
            if reg.armable():
                self._fast_armed = True
                _armed_list(reg.cid).append(self)
                return self
        self._inner = self._make()
        return self

    def _demote(self) -> None:
        """Move a fast-armed round onto the legacy worker path (initiation
        happens NOW, preserving Start order for whatever follows)."""
        if not self._fast_armed:
            return
        self._fast_armed = False
        lst = _armed_list(self._reg.cid)
        if self in lst:
            lst.remove(self)
        self._inner = self._make()

    @property
    def active(self) -> bool:
        return self._fast_armed or \
            (self._inner is not None and self._inner.active)

    @property
    def progress(self) -> Optional[ChunkProgress]:
        return getattr(self._inner, "progress", None)

    def test(self) -> bool:
        if self._fast_armed:
            # Test must not block: hand the round to the worker and poll
            # it. Demote the comm's WHOLE armed stack — initiation order
            # is Start order, so earlier stacked rounds must reach the
            # worker before (and later ones may not stay deferred behind)
            # this one.
            demote_fast_armed(self._reg.cid)
        if self._inner is None:
            return True
        done = self._inner.test()
        if done:
            self.result = self._inner.result
        return done

    def wait(self):
        from .pointtopoint import STATUS_EMPTY
        if self._fast_armed:
            # completes every armed round up to ours in Start order —
            # batched through one rendezvous wakeup when stacked
            flush_fast_armed(self._reg.cid, upto=self)
            return self.status
        if self._inner is None:
            return self.status or STATUS_EMPTY
        # Wait-time ownership (the outermost-owner rule, ISSUE-6 bugfix):
        # the round's wall clock is already fully accounted by the op scope
        # its worker owns (phase_ns + times), so the inner CollRequest.wait
        # must not ALSO bump wait_ns for the same interval.
        from . import perfvars as _pv
        claimed = _pv.own_wait()
        try:
            self.status = self._inner.wait()
        finally:
            if claimed:
                _pv.disown_wait()
        self.result = self._inner.result
        self._inner = None          # inactive, ready for the next Start
        self._trace_complete()
        return self.status

    def _consume(self):
        from .pointtopoint import STATUS_EMPTY
        if self._fast_armed:
            return self.wait()
        if self._inner is None:
            return self.status or STATUS_EMPTY
        from . import perfvars as _pv
        claimed = _pv.own_wait()
        try:
            self.status = self._inner.wait() if self._inner.active \
                else (self._inner.status or STATUS_EMPTY)
        finally:
            if claimed:
                _pv.disown_wait()
        self.result = self._inner.result
        self._inner = None
        self._trace_complete()
        return self.status

    def _trace_complete(self) -> None:
        """Record the Wait that completed round ``self._round - 1`` and pin
        its result object (identity anchor for R302's invalidation window)."""
        from .analyze import events as _ev
        if not _ev.enabled() or self._comm is None:
            return
        rnd = self._round - 1
        self._results.append((rnd, self.result))
        _ev.record_wait(self._comm, self.kind, id(self), rnd,
                        result=self.result)

    def cancel(self) -> None:
        raise MPIError("nonblocking collectives cannot be cancelled")

    def __repr__(self) -> str:
        return f"<PersistentCollRequest {self.kind} active={self.active}>"
