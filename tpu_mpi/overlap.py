"""Host-path overlap engine: chunk schedules, persistent collective plans,
and in-flight progress state (ISSUE-3 tentpole).

Three coordinated pieces, shared by the thread tier (``_runtime
.CollectiveChannel``), the multi-process tier (``backend.ProcChannel``'s
chunked star) and the nonblocking machinery (``collective._nb_submit``):

- :class:`ChunkSchedule` — how a bulk payload splits into K pipeline chunks
  (``config.pipeline_min_bytes`` / ``config.pipeline_chunks``, the
  ``shm_min_bytes`` knob pattern). Chunking is only ever applied to
  elementwise rank-order folds, where it is *chunk-separable*: the pipelined
  result is bitwise-identical to the monolithic one.
- :class:`PlanCache` / :class:`CollectivePlan` — repeated same-shape
  collectives (the training-loop case) resolve their op, combine closure,
  opname tag, trace signature and chunk schedule ONCE and reuse the plan;
  keyed on (comm, op, dtype, shape, flavor) and invalidated by
  ``Comm.free`` and by config reloads (``config.GENERATION``).
- :class:`ChunkProgress` — per-request in-flight chunk state that the
  progress threads (the per-comm nonblocking worker; the multi-process
  drainer feeding it) advance while the rank thread is in user code, and
  that ``Wait``/``Test`` join instead of executing the whole op.

:class:`PersistentCollRequest` is the persistent-collective handle behind
``Allreduce_init``-style APIs (MPI-4 persistent collectives), mirroring the
persistent P2P machinery (:class:`tpu_mpi.pointtopoint.Prequest`).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from typing import Any, Callable, Optional

from . import error as _ec
from .error import MPIError


class ChunkSchedule:
    """A bulk payload's split into pipeline chunks.

    ``bounds`` is a list of flat-element ``(lo, hi)`` half-open ranges
    covering ``[0, count)`` in order. Every chunk has ``base`` elements and
    the LAST chunk absorbs the remainder (``count % nchunks``), so uneven
    payloads never produce an empty chunk and never reorder elements —
    chunked rank-order folds stay bitwise-equal to monolithic ones.
    """

    __slots__ = ("count", "nchunks", "bounds")

    def __init__(self, count: int, nchunks: int):
        count, nchunks = int(count), int(nchunks)
        nchunks = max(1, min(nchunks, count))
        base = count // nchunks
        self.count = count
        self.nchunks = nchunks
        self.bounds = [(i * base, (i + 1) * base if i < nchunks - 1 else count)
                       for i in range(nchunks)]

    @classmethod
    def maybe(cls, count: int, itemsize: int) -> Optional["ChunkSchedule"]:
        """The schedule for a payload, or None when pipelining is off or
        the payload is below ``pipeline_min_bytes`` (monolithic path)."""
        from . import config
        cfg = config.load()
        if cfg.pipeline_min_bytes <= 0 or cfg.pipeline_chunks < 2:
            return None
        if int(count) * int(itemsize) < cfg.pipeline_min_bytes:
            return None
        sched = cls(count, cfg.pipeline_chunks)
        return sched if sched.nchunks > 1 else None

    def __iter__(self):
        return iter(self.bounds)

    def __len__(self) -> int:
        return self.nchunks

    def __repr__(self) -> str:
        return f"ChunkSchedule({self.count} elems x {self.nchunks} chunks)"


class CollectivePlan:
    """Everything a repeated same-signature collective can pre-resolve:
    the resolved :class:`~tpu_mpi.operators.Op`, the rendezvous combine
    closure, the opname tag, the trace-verifier signature, the algorithm
    hint for the multi-process tier (carrying the ``tune.select`` decision,
    so the algorithm is resolved once per signature and invalidated with
    the plan), and the chunk schedule."""

    __slots__ = ("opname", "op", "combine", "sig", "hint", "schedule",
                 "generation", "algo")

    def __init__(self, opname: str, op: Any, combine: Callable, sig: dict,
                 hint: Any, schedule: Optional[ChunkSchedule],
                 generation: int, algo: str = "star"):
        self.opname = opname
        self.op = op
        self.combine = combine
        self.sig = sig
        self.hint = hint
        self.schedule = schedule
        self.generation = generation
        self.algo = algo


class PlanCache:
    """Bounded LRU of :class:`CollectivePlan` keyed on the collective's
    full call signature: (cid, family, op identity, count, dtype, array
    kind, flavor). Entries from a stale ``config.GENERATION`` miss (the
    pipeline knobs feed the schedule), and :meth:`invalidate` drops a
    freed communicator's plans. Unhashable keys (an unhashable custom op)
    simply never cache."""

    CAP = 128

    def __init__(self):
        self._lock = threading.Lock()
        self._plans: "OrderedDict[Any, CollectivePlan]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Any) -> Optional[CollectivePlan]:
        from . import config
        try:
            hash(key)
        except TypeError:
            return None
        with self._lock:
            plan = self._plans.get(key)
            if plan is not None and plan.generation == config.GENERATION:
                self._plans.move_to_end(key)
                self.hits += 1
                return plan
            if plan is not None:                 # stale config generation
                del self._plans[key]
            self.misses += 1
            return None

    def put(self, key: Any, plan: CollectivePlan) -> None:
        try:
            hash(key)
        except TypeError:
            return
        with self._lock:
            self._plans[key] = plan
            self._plans.move_to_end(key)
            while len(self._plans) > self.CAP:
                self._plans.popitem(last=False)

    def invalidate(self, cid: Any = None) -> None:
        """Drop every plan (no args) or one communicator's plans
        (``Comm.free``)."""
        with self._lock:
            if cid is None:
                self._plans.clear()
                return
            for k in [k for k in self._plans if k[0] == cid]:
                del self._plans[k]

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._plans), "hits": self.hits,
                    "misses": self.misses}


#: The process-wide plan cache. ``Comm.free`` invalidates per-cid; config
#: reloads invalidate by generation.
plans = PlanCache()


class ChunkProgress:
    """In-flight chunk state for one nonblocking collective, advanced by
    whichever progress thread moves the op (the per-comm worker; at a
    multi-process star root, the fold loop fed by the drainer) and read by
    ``Test``/``Wait`` and by benchmarks. ``total`` is 0 until the op's
    chunk schedule is known (monolithic ops never set it)."""

    __slots__ = ("done", "total", "stage")

    def __init__(self):
        self.done = 0
        self.total = 0
        self.stage = "pending"

    def begin(self, total: int, stage: str) -> None:
        self.total = int(total)
        self.done = 0
        self.stage = stage

    def note(self, done: Optional[int] = None) -> None:
        self.done = self.done + 1 if done is None else int(done)

    def __repr__(self) -> str:
        return f"<ChunkProgress {self.stage} {self.done}/{self.total}>"


_progress_tls = threading.local()


def bind_progress(prog: Optional[ChunkProgress]) -> None:
    """Bind the progress record the current thread's collective work should
    advance (set by the nonblocking worker around each op; None clears)."""
    _progress_tls.current = prog


def current_progress() -> Optional[ChunkProgress]:
    return getattr(_progress_tls, "current", None)


def progress_begin(total: int, stage: str) -> Optional[ChunkProgress]:
    prog = current_progress()
    if prog is not None:
        prog.begin(total, stage)
    return prog


def progress_note(prog: Optional[ChunkProgress]) -> None:
    if prog is not None:
        prog.note()


class PlanRegistration:
    """Plan-bound registered buffers + the pre-resolved round closure of one
    persistent collective (the ISSUE-6 tentpole). Built once at
    ``Allreduce_init`` by :func:`tpu_mpi.collective._register_allreduce`:
    arguments parsed, wire views pinned, the fold scratch pre-allocated
    (``buffers.register_scratch``), the combine / copy-out pre-bound — a
    Start/Wait round is then one inline rendezvous with zero allocation,
    no plan lookup and no worker hop. Tracked in :data:`registry` so
    ``Comm.free`` releases the pinned buffers and any shm slot lease."""

    __slots__ = ("cid", "generation", "scratch", "wire", "run_round",
                 "shm_release", "released", "knob_on", "_nb_probe",
                 "inplace_optin")

    def __init__(self, cid: int, generation: int, run_round: Callable[[], Any],
                 scratch: tuple = (), wire: Any = None,
                 shm_release: Optional[Callable[[], None]] = None,
                 knob_on: bool = True, nb_probe: Optional[Callable] = None,
                 inplace_optin: bool = False):
        self.cid = cid
        self.generation = generation
        self.run_round = run_round
        self.scratch = scratch          # pinned fold accumulators (id-stable)
        self.wire = wire                # pre-bound send wire view, if host
        self.shm_release = shm_release
        self.released = False
        self.knob_on = knob_on
        self._nb_probe = nb_probe       # () -> outstanding nb ops on the comm
        self.inplace_optin = inplace_optin

    def armable(self) -> bool:
        """Whether a Start may take the fast path right now: the knob is on,
        the run is untraced (traced runs keep the fully-evented legacy
        path), and this comm's nonblocking worker is idle (in-flight ``I*``
        ops own the initiation order)."""
        if self.released or not self.knob_on:
            return False
        from .analyze import events as _ev
        if _ev.enabled():
            return False
        return self._nb_probe is None or self._nb_probe() == 0

    def release(self) -> None:
        """Drop the pinned buffers and any shm slot lease (``Comm.free``)."""
        if self.released:
            return
        self.released = True
        self.scratch = ()
        self.wire = None
        rel, self.shm_release = self.shm_release, None
        if rel is not None:
            rel()


class BufferRegistry:
    """Process-wide registry of live :class:`PlanRegistration` instances,
    keyed by communicator cid. ``Comm.free`` calls :meth:`release` so plan-
    registered wire buffers and shm segment slots never outlive their
    communicator (the ISSUE-6 leak fix); ``TPU_MPI_STRICT`` asserts the
    lease count actually hit zero."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_cid: dict[Any, list] = {}

    def add(self, reg: PlanRegistration) -> PlanRegistration:
        with self._lock:
            self._by_cid.setdefault(reg.cid, []).append(reg)
        return reg

    def release(self, cid: Any) -> int:
        """Release every registration of one communicator; returns how many
        were released."""
        with self._lock:
            regs = self._by_cid.pop(cid, [])
        for reg in regs:
            reg.release()
        return len(regs)

    def leased(self, cid: Any = None) -> int:
        """Outstanding shm slot leases (one comm, or all) — the strict-mode
        refcount the ``Comm.free`` assert reads."""
        with self._lock:
            regs = [r for k, rs in self._by_cid.items()
                    if cid is None or k == cid for r in rs]
        return sum(1 for r in regs if r.shm_release is not None
                   and not r.released)

    def stats(self) -> dict:
        with self._lock:
            return {"comms": len(self._by_cid),
                    "registrations": sum(len(v) for v in self._by_cid.values())}


#: Live plan registrations; ``Comm.free`` releases per-cid.
registry = BufferRegistry()


_fast_tls = threading.local()     # .armed: {cid: [PersistentCollRequest]}


def _armed_list(cid: Any) -> list:
    armed = getattr(_fast_tls, "armed", None)
    if armed is None:
        armed = _fast_tls.armed = {}
    lst = armed.get(cid)
    if lst is None:
        lst = armed[cid] = []
    return lst


def demote_fast_armed(cid: Any = None) -> None:
    """Push every fast-armed persistent request on THIS thread (of one comm,
    or of all comms) onto the legacy worker path, in Start order. Called
    before anything else initiates on the same communicator — a blocking
    collective (``collective._ordered_run``), a nonblocking submit
    (``collective._nb_submit``), or a second Start — so initiation order
    stays the program order even though fast-armed rounds defer their
    rendezvous to ``Wait``."""
    armed = getattr(_fast_tls, "armed", None)
    if not armed:
        return
    cids = [cid] if cid is not None else list(armed)
    for c in cids:
        for req in list(armed.get(c, ())):
            req._demote()


class PersistentCollRequest:
    """Persistent collective request (MPI-4 ``MPI_Allreduce_init`` family),
    mirroring :class:`tpu_mpi.pointtopoint.Prequest`: created INACTIVE with
    the operation's arguments bound (and its plan pre-resolved), armed by
    ``Start``/``Startall``, completed by the whole Wait/Test family, then
    inactive-but-reusable for the next round.

    Two execution lanes. The **registered fast path** (a
    :class:`PlanRegistration` bound via :meth:`bind_registration`, the
    default when the operands are eligible): Start arms the round and Wait
    runs it INLINE on the calling thread against the pre-pinned buffers —
    one rendezvous round trip, zero allocation. The **legacy lane**: each
    Start initiates the collective on this rank's per-comm worker, so
    rounds progress in the background exactly like the one-shot ``I*``
    ops; Test on a fast-armed round demotes to this lane (Test must not
    block)."""

    def __init__(self, make: Callable[[], Any], kind: str, buffer: Any,
                 comm: Any = None):
        self._make = make           # () -> a live CollRequest
        self._inner = None
        self.kind = kind            # e.g. "pallreduce"
        self.buffer = buffer
        self.status = None
        self.result = None          # allocating flavors: last round's value
        self._reg: Optional[PlanRegistration] = None
        self._reg_factory: Optional[Callable[[], Any]] = None
        self._fast_armed = False
        # tracing state (tpu_mpi.analyze): the comm the Start/Wait events
        # record against, rounds started so far, and strong refs to recent
        # round results so R302's invalidation ids stay unrecycled.
        self._comm = comm
        self._round = 0
        self._results: deque = deque(maxlen=4)

    def bind_registration(self, factory: Callable[[], Any]
                          ) -> "PersistentCollRequest":
        """Attach the registered-buffer fast path: ``factory()`` builds a
        :class:`PlanRegistration` (or None when the operands are not
        eligible) and is re-run to rebind buffers after a config-generation
        change."""
        self._reg_factory = factory
        self._reg = factory()
        return self

    @property
    def registration(self) -> Optional[PlanRegistration]:
        """The live registration (None = generic path). Exposed for tests
        and benchmarks asserting id-stable pinned buffers."""
        return self._reg

    def start(self) -> "PersistentCollRequest":
        if self.active:
            raise MPIError("Start on an already-active persistent request",
                           code=_ec.ERR_REQUEST)
        from .analyze import events as _ev
        if _ev.enabled() and self._comm is not None:
            # R302 front end: on the donated fast path, this Start re-donates
            # the 2-slot fold ring entry holding round (k-2)'s result — name
            # that buffer so the race pass can flag reads-after-invalidation.
            inval = None
            for rnd, res in self._results:
                if rnd == self._round - 2:
                    inval = _ev.buf_id(res)
            _ev.record_start(self._comm, self.kind, id(self), self._round,
                             invalidates=inval)
        self._round += 1
        reg = self._reg
        if reg is not None:
            from . import config
            if reg.generation != config.GENERATION \
                    and self._reg_factory is not None:
                # config reload: rebind the registered buffers (the pipeline
                # knobs feed the schedule; the knob itself may have flipped)
                reg = self._reg = self._reg_factory()
        if reg is not None and reg.armable():
            lst = _armed_list(reg.cid)
            if lst:
                # a second Start on the same comm: demote the earlier armed
                # rounds to the worker (initiation order = Start order);
                # the worker is then busy, so this round goes legacy too
                demote_fast_armed(reg.cid)
            if reg.armable():
                self._fast_armed = True
                _armed_list(reg.cid).append(self)
                return self
        self._inner = self._make()
        return self

    def _demote(self) -> None:
        """Move a fast-armed round onto the legacy worker path (initiation
        happens NOW, preserving Start order for whatever follows)."""
        if not self._fast_armed:
            return
        self._fast_armed = False
        lst = _armed_list(self._reg.cid)
        if self in lst:
            lst.remove(self)
        self._inner = self._make()

    @property
    def active(self) -> bool:
        return self._fast_armed or \
            (self._inner is not None and self._inner.active)

    @property
    def progress(self) -> Optional[ChunkProgress]:
        return getattr(self._inner, "progress", None)

    def test(self) -> bool:
        if self._fast_armed:
            # Test must not block: hand the round to the worker and poll it
            self._demote()
        if self._inner is None:
            return True
        done = self._inner.test()
        if done:
            self.result = self._inner.result
        return done

    def wait(self):
        from .pointtopoint import STATUS_EMPTY
        if self._fast_armed:
            self._fast_armed = False
            lst = _armed_list(self._reg.cid)
            if self in lst:
                lst.remove(self)
            self.result = self._reg.run_round()
            self.status = STATUS_EMPTY
            self._trace_complete()
            return self.status
        if self._inner is None:
            return self.status or STATUS_EMPTY
        # Wait-time ownership (the outermost-owner rule, ISSUE-6 bugfix):
        # the round's wall clock is already fully accounted by the op scope
        # its worker owns (phase_ns + times), so the inner CollRequest.wait
        # must not ALSO bump wait_ns for the same interval.
        from . import perfvars as _pv
        claimed = _pv.own_wait()
        try:
            self.status = self._inner.wait()
        finally:
            if claimed:
                _pv.disown_wait()
        self.result = self._inner.result
        self._inner = None          # inactive, ready for the next Start
        self._trace_complete()
        return self.status

    def _consume(self):
        from .pointtopoint import STATUS_EMPTY
        if self._fast_armed:
            return self.wait()
        if self._inner is None:
            return self.status or STATUS_EMPTY
        from . import perfvars as _pv
        claimed = _pv.own_wait()
        try:
            self.status = self._inner.wait() if self._inner.active \
                else (self._inner.status or STATUS_EMPTY)
        finally:
            if claimed:
                _pv.disown_wait()
        self.result = self._inner.result
        self._inner = None
        self._trace_complete()
        return self.status

    def _trace_complete(self) -> None:
        """Record the Wait that completed round ``self._round - 1`` and pin
        its result object (identity anchor for R302's invalidation window)."""
        from .analyze import events as _ev
        if not _ev.enabled() or self._comm is None:
            return
        rnd = self._round - 1
        self._results.append((rnd, self.result))
        _ev.record_wait(self._comm, self.kind, id(self), rnd,
                        result=self.result)

    def cancel(self) -> None:
        raise MPIError("nonblocking collectives cannot be cancelled")

    def __repr__(self) -> str:
        return f"<PersistentCollRequest {self.kind} active={self.active}>"
