"""Communicators: ordered device/rank groups bound to the SPMD world.

Reference: /root/reference/src/comm.jl — Comm handle (:6), COMM_NULL/WORLD/SELF
(:8-23), Comm_rank (:49-53), Comm_size (:66-70), Comm_dup (:78-84),
Comm_split (:92-99), Comm_split_type (:107-115), Comm_get_parent (:123-127),
Comm_spawn (:135-147), Intercomm_merge (:155-162), universe_size (:171-181),
Comm_compare + Comparison enum (:197-218).

TPU mapping (SURVEY.md §2.2): a Comm is an ordered subset of the world's ranks
(each rank owning a device); ``Comm_split`` regroups ranks into sub-worlds. A
communicator's *context id* (cid) isolates its point-to-point and collective
traffic, allocated collectively on the parent so all members agree — the analog
of MPI context ids that libmpi manages internally.
"""

from __future__ import annotations

import enum
from typing import Optional, Sequence

from ._runtime import UNDEFINED, CollectiveChannel, current_env, require_env
from . import error as _ec
from .error import InvalidCommError, MPIError


class Comparison(enum.IntEnum):
    """Result of Comm_compare (src/comm.jl:197-204)."""
    IDENT = 0
    CONGRUENT = 1
    SIMILAR = 2
    UNEQUAL = 3


IDENT = Comparison.IDENT
CONGRUENT = Comparison.CONGRUENT
SIMILAR = Comparison.SIMILAR
UNEQUAL = Comparison.UNEQUAL

# Split type for Comm_split_type (src/comm.jl:107-115): ranks sharing a host.
COMM_TYPE_SHARED = 1

# MPI_ROOT sentinel for rooted intercomm collectives: in the root group, the
# one sourcing rank passes ROOT and the rest pass PROC_NULL; the receiving
# group passes the root's rank within the remote group. (The value is this
# runtime's own sentinel, like _runtime.PROC_NULL — the reference inherits
# libmpi's, consts_mpich.jl.)
ROOT = -4


class Comm:
    """An ordered group of ranks with an isolated communication context.

    ``group[i]`` is the world rank of this communicator's rank i; the calling
    rank's position defines ``Comm_rank``.
    """

    def __init__(self, group: Sequence[int], cid: int, *, ctx=None, name: str = "comm"):
        self._group = tuple(group)
        self._cid = cid
        self._fixed_ctx = ctx
        self.name = name
        self._freed = False
        self._rank_cache: dict[int, int] = {}

    # -- context / group resolution -----------------------------------------
    @property
    def ctx(self):
        if self._fixed_ctx is not None:
            return self._fixed_ctx
        ctx, _ = require_env()
        return ctx

    @property
    def group(self) -> tuple[int, ...]:
        return self._group

    @property
    def cid(self) -> int:
        return self._cid

    def _check(self) -> None:
        if self._freed:
            raise InvalidCommError("operation on a freed communicator")

    def rank(self) -> int:
        self._check()
        _, world_rank = require_env()
        # per-world-rank cache: list.index() on every Send/Recv is
        # measurable on the small-message latency lane
        r = self._rank_cache.get(world_rank)
        if r is None:
            try:
                r = self._group.index(world_rank)
            except ValueError:
                raise InvalidCommError(
                    f"world rank {world_rank} is not a member of "
                    f"{self.name}") from None
            self._rank_cache[world_rank] = r
        return r

    def size(self) -> int:
        self._check()
        return len(self._group)

    def world_rank_of(self, comm_rank: int) -> int:
        """Translate a rank of this communicator to a world rank."""
        return self._group[comm_rank]

    def channel(self) -> CollectiveChannel:
        """The collective rendezvous channel for this communicator."""
        self._check()
        ctx = self.ctx
        if ctx.failed_ranks or ctx.revoked_cids:  # fault path is pay-for-use
            ctx.check_fault(self._cid)
        return ctx.channel(self._cid, len(self._group), group=self._group)

    def get_pvars(self, reset: bool = False) -> dict:
        """This rank's performance-variable snapshot on this communicator
        (docs/observability.md): byte/op counters, per-collective latency
        stats and histograms, host-path phase times, RMA epoch counts.
        ``reset=True`` additionally zeroes the counters (MPI_T pvar
        read-and-reset semantics)."""
        self._check()
        from . import perfvars
        return perfvars.comm_snapshot(self, reset=reset)

    @property
    def device(self):
        """The JAX device owned by the calling rank (SURVEY.md §2.3: buffers
        are device-resident by construction; each rank binds one device)."""
        ctx, world_rank = require_env()
        return ctx.device_for(world_rank)

    def free(self) -> None:
        """Mark the communicator unusable and release this rank's
        nonblocking-collective worker thread, if one was created
        (src/comm.jl MPI_Comm_free analog — no C resources, but the
        I-collective executor is a real thread).

        Freeing under in-flight nonblocking collectives is a typed error
        naming the pending ops — Wait them first. (MPI_Comm_free's deferred
        destruction has no analog here: the worker thread and the plan/
        registry entries go away NOW, so completing the pending ops later
        is impossible; silently shooting them down is how a broker bug
        would masquerade as a tenant bug — docs/serving.md lease
        reclamation depends on telling the two apart.)"""
        pre_env = current_env()
        if pre_env is not None:
            from .collective import nb_pending
            pending = nb_pending(pre_env[0], self._cid, pre_env[1])
            if pending:
                raise MPIError(
                    f"Comm.free on {self.name} (cid={self._cid}) with "
                    f"{len(pending)} in-flight nonblocking op(s): "
                    f"{', '.join(pending)} — Wait/Test them to completion "
                    f"before freeing", code=_ec.ERR_PENDING)
        self._freed = True
        from .overlap import plans, registry
        plans.invalidate(self._cid)   # cached collective plans die with us
        # registered fast path (docs/performance.md "Registered buffers"):
        # plan-pinned wire views, fold scratch and shm slot leases must not
        # outlive the communicator
        registry.release(self._cid)
        env = current_env()
        if env is not None:
            from .collective import nb_shutdown
            ctx, world_rank = env
            nb_shutdown(ctx, cid=self._cid, world_rank=world_rank)
            ch = ctx._channels.get(self._cid) \
                if hasattr(ctx, "_channels") else None
            drop = getattr(ch, "drop_shm", None)
            if drop is not None:
                drop()
        from . import config
        if config.load().strict:
            leaked = registry.leased(self._cid)
            assert leaked == 0, (
                f"Comm.free left {leaked} registered shm slot lease(s) on "
                f"cid {self._cid} — a PlanRegistration escaped the registry")

    def py2f(self) -> int:
        return self._cid

    def __repr__(self) -> str:
        return f"<Comm {self.name} cid={self._cid} size={len(self._group)}>"


class _WorldComm(Comm):
    """COMM_WORLD: the calling rank's *job world*, resolved dynamically so the
    module-level constant works on every rank-thread (src/comm.jl:13-17).
    Ranks created by Comm_spawn form their own world, exactly as spawned MPI
    jobs get their own MPI_COMM_WORLD."""

    def __init__(self):
        super().__init__((), 0, name="COMM_WORLD")

    @property
    def group(self) -> tuple[int, ...]:
        ctx, world_rank = require_env()
        return ctx.world_of(world_rank)[0]

    @property
    def cid(self):
        ctx, world_rank = require_env()
        return ctx.world_of(world_rank)[1]

    def rank(self) -> int:
        ctx, world_rank = require_env()
        return ctx.world_of(world_rank)[0].index(world_rank)

    def size(self) -> int:
        ctx, world_rank = require_env()
        return len(ctx.world_of(world_rank)[0])

    def world_rank_of(self, comm_rank: int) -> int:
        ctx, world_rank = require_env()
        return ctx.world_of(world_rank)[0][comm_rank]

    def channel(self) -> CollectiveChannel:
        ctx, world_rank = require_env()
        group, cid = ctx.world_of(world_rank)
        return ctx.channel(cid, len(group), group=group)


class _SelfComm(Comm):
    """COMM_SELF: just the calling rank (src/comm.jl:19-23)."""

    def __init__(self):
        super().__init__((), 1, name="COMM_SELF")

    @property
    def group(self) -> tuple[int, ...]:
        _, world_rank = require_env()
        return (world_rank,)

    def rank(self) -> int:
        return 0

    def size(self) -> int:
        return 1

    def world_rank_of(self, comm_rank: int) -> int:
        _, world_rank = require_env()
        return world_rank

    def channel(self) -> CollectiveChannel:
        ctx, world_rank = require_env()
        # Per-rank channel: cid 1 is logically distinct per rank; key it so.
        return ctx.channel((1, world_rank), 1, group=(world_rank,))


class _NullComm(Comm):
    """COMM_NULL sentinel (src/comm.jl:8-11)."""

    def __init__(self):
        super().__init__((), -1, name="COMM_NULL")

    def rank(self) -> int:
        raise InvalidCommError("Comm_rank on COMM_NULL")

    def size(self) -> int:
        raise InvalidCommError("Comm_size on COMM_NULL")

    def channel(self):
        raise InvalidCommError("collective on COMM_NULL")


COMM_WORLD = _WorldComm()
COMM_SELF = _SelfComm()
COMM_NULL = _NullComm()


def Comm_rank(comm: Comm) -> int:
    """Rank of the calling process in comm (src/comm.jl:49-53)."""
    return comm.rank()


def Comm_size(comm: Comm) -> int:
    """Number of ranks in comm (src/comm.jl:66-70)."""
    return comm.size()


def _record_coll(comm: Comm, opname: str) -> None:
    """Trace hook for the comm-management collectives, which rendezvous
    directly on the channel rather than through collective._run."""
    from .analyze import events as _ev
    if _ev.enabled():
        _ev.record_collective(comm, opname)


def Comm_dup(comm: Comm) -> Comm:
    """Collective: duplicate comm with a fresh context id (src/comm.jl:78-84)."""
    _record_coll(comm, f"Comm_dup@{comm.cid}")
    my_rank = comm.rank()
    group = comm.group

    def combine(contribs):
        ctx = comm.ctx
        cid = ctx.alloc_cid()
        return [cid] * len(contribs)

    cid = comm.channel().run(my_rank, None, combine, f"Comm_dup@{comm.cid}")
    return Comm(group, cid, name=f"{comm.name}.dup")


def Comm_split(comm: Comm, color: Optional[int], key: int) -> Comm:
    """Collective: partition ranks by color, order by (key, rank)
    (src/comm.jl:92-99). ``color=None`` (UNDEFINED) returns COMM_NULL."""
    _record_coll(comm, f"Comm_split@{comm.cid}")
    my_rank = comm.rank()
    group = comm.group
    c = UNDEFINED if color is None else int(color)

    def combine(contribs):
        ctx = comm.ctx
        colors: dict[int, list[tuple[int, int]]] = {}
        for r, (col, k) in enumerate(contribs):
            if col != UNDEFINED:
                colors.setdefault(col, []).append((k, r))
        new_comms: dict[int, tuple[tuple[int, ...], int]] = {}
        for col in sorted(colors):
            members = [r for (_, r) in sorted(colors[col])]
            new_group = tuple(group[r] for r in members)
            new_comms[col] = (new_group, ctx.alloc_cid())
        out = []
        for r, (col, _) in enumerate(contribs):
            out.append(None if col == UNDEFINED else new_comms[col])
        return out

    res = comm.channel().run(my_rank, (c, int(key)), combine, f"Comm_split@{comm.cid}")
    if res is None:
        return COMM_NULL
    new_group, cid = res
    return Comm(new_group, cid, name=f"{comm.name}.split({c})")


def Comm_split_type(comm: Comm, split_type: int, key: int) -> Comm:
    """Split into groups that can share memory (src/comm.jl:107-115).

    Each rank contributes its backend ``host_token`` (thread tier: one
    address space, one token; multi-process tier: the rank's transport
    address host, or the TPU_MPI_HOST_ID override) to a rendezvous, and the
    color is the lowest comm rank holding the same token — so a multi-host
    ``--procs`` world splits into genuine per-host groups instead of one
    bogus world-wide "shared" group (VERDICT r2 missing #2)."""
    if split_type != COMM_TYPE_SHARED:
        return Comm_split(comm, None, key)

    def combine(tokens):
        first = {}
        for r, tok in enumerate(tokens):
            first.setdefault(tok, r)
        return [first[tok] for tok in tokens]

    color = comm.channel().run(comm.rank(), comm.ctx.host_token, combine,
                               f"Comm_split_type@{comm.cid}")
    return Comm_split(comm, color, key)


# ---------------------------------------------------------------------------
# ULFM-shaped fault tolerance: Comm_revoke / Comm_agree / Comm_shrink
# (MPI 4.x User-Level Failure Mitigation surface; docs/fault-tolerance.md)
# ---------------------------------------------------------------------------

def _next_epoch(ctx, cid, world_rank) -> int:
    """Per-communicator agreement epoch: this rank's own call count.
    Comm_agree/Comm_shrink are collective, so every member advances its
    counter in lockstep and the epochs align without communication. Keyed by
    (cid, rank) — in the thread tier ``ctx`` is SHARED by all rank threads,
    and a shared per-cid counter would interleave."""
    seq = getattr(ctx, "_agree_seq", None)
    if seq is None:
        seq = ctx._agree_seq = {}
    e = seq.get((cid, world_rank), 0) + 1
    seq[(cid, world_rank)] = e
    return e


def Comm_revoke(comm: Comm) -> None:
    """Revoke the communicator after a failure (MPI_Comm_revoke analog).

    Non-collective: any member may call it. Every pending and future
    operation on the communicator — on every member that learns of the
    revocation — raises :class:`~tpu_mpi.error.RevokedError` instead of
    hanging on a dead peer. Only Comm_agree and Comm_shrink remain legal.
    Multi-process tier: a revoke frame is flooded to the group and each
    receiver re-floods once, so propagation completes even if the original
    caller dies mid-flood."""
    comm._check()
    ctx = comm.ctx
    _record_coll(comm, f"Comm_revoke@{comm.cid}")
    from .analyze import events as _ev
    if _ev.enabled():
        _ev.record_ft(comm, "Comm_revoke")
    ctx.revoke_comm(comm.cid)
    flood = getattr(ctx, "flood", None)
    if flood is not None:
        flood(comm.group, ("revoke", comm.cid, tuple(comm.group)))


def Comm_agree(comm: Comm, flag: int = 1) -> int:
    """Fault-tolerant agreement (MPI_Comm_agree analog): returns the bitwise
    AND of every live member's ``flag``. Works on a revoked communicator and
    completes despite concurrent member failures — the recovery path's
    decision primitive ("did everyone succeed / shall we shrink?")."""
    comm._check()
    ctx, world_rank = require_env()
    _record_coll(comm, f"Comm_agree@{comm.cid}")
    epoch = _next_epoch(ctx, comm.cid, world_rank)
    value, dead = ctx.ft_agree(world_rank, comm.group, comm.cid, epoch,
                               int(flag))
    from .analyze import events as _ev
    if _ev.enabled():
        # T207 front end: every member must report the same epoch/value/dead
        # view for this agreement, or the recovery protocol has diverged
        _ev.record_ft(comm, "Comm_agree", epoch=epoch, dead=dead, value=value)
    return value


def Comm_shrink(comm: Comm) -> Comm:
    """Build the survivor communicator (MPI_Comm_shrink analog).

    Collective over the LIVE members: agrees on the union of everyone's
    failed-rank views, then forms a new communicator of the survivors in
    group order. The new context id is derived deterministically from the
    agreement — ``("shrink", old_cid, epoch)`` — so no rendezvous through a
    (possibly dead) root is needed. Dead-rank state tied to the old
    communicator (collective channel, cached overlap plans) is drained
    before the replacement goes live."""
    comm._check()
    ctx, world_rank = require_env()
    _record_coll(comm, f"Comm_shrink@{comm.cid}")
    epoch = _next_epoch(ctx, comm.cid, world_rank)
    _value, dead = ctx.ft_agree(world_rank, comm.group, comm.cid, epoch, 1)
    survivors = tuple(r for r in comm.group if r not in dead)
    from .analyze import events as _ev
    if _ev.enabled():
        _ev.record_ft(comm, "Comm_shrink", epoch=epoch, survivors=survivors,
                      dead=dead)
    drain = getattr(ctx, "drain_failed_state", None)
    if drain is not None:
        drain(comm.cid)
    if world_rank not in survivors:
        return COMM_NULL
    if not dead:
        # nothing failed (e.g. the thread tier, where ranks share a
        # process): still a fresh communicator, via the ordinary collective
        # cid allocation — the channel combine runs alloc_cid once
        new_cid = ctx.channel(("ftshrink", comm.cid, epoch), len(survivors),
                              survivors).run(
            survivors.index(world_rank), None,
            lambda contribs: [ctx.alloc_cid()] * len(contribs),
            f"Comm_shrink@{comm.cid}")
    else:
        new_cid = ("shrink", comm.cid, epoch)
    # register the survivor channel EAGERLY with its group: check_fault
    # consults the channel's group to scope failures, which is what lets a
    # shrunk communicator keep operating while failed_ranks stays non-empty
    ctx.channel(new_cid, len(survivors), survivors)
    return Comm(survivors, new_cid, name=f"{comm.name}.shrink")


class Intercomm(Comm):
    """An inter-communicator: a local group plus a remote group sharing one
    context (src/comm.jl:135-162). Point-to-point ranks address the *remote*
    group, per MPI intercomm semantics; Comm_rank/Comm_size are local."""

    def __init__(self, local_group: Sequence[int], remote_group: Sequence[int],
                 cid: int, name: str = "intercomm"):
        super().__init__(local_group, cid, name=name)
        self.remote_group = tuple(remote_group)

    def remote_size(self) -> int:
        return len(self.remote_group)

    def world_rank_of(self, comm_rank: int) -> int:
        # dest/src in P2P over an intercomm are remote-group ranks.
        return self.remote_group[comm_rank]

    def channel(self) -> CollectiveChannel:
        # Intercomm collectives have two-group semantics the intracomm
        # rendezvous cannot express (both sides would deposit into overlapping
        # local-rank slots of one cid-keyed channel). Barrier/Bcast/bcast use
        # the two-group channel with MPI_ROOT semantics (collective.py); for
        # the rest, Intercomm_merge into an intracommunicator first.
        raise MPIError("only Barrier/Bcast/bcast are supported on an "
                       "intercommunicator; Intercomm_merge it into an "
                       "intracommunicator for other collectives",
                       code=_ec.ERR_COMM)

    def two_group_slots(self) -> tuple[tuple[int, ...], tuple[int, ...], int]:
        """Canonical rendezvous ordering across both groups (shared with
        Intercomm_merge): the group containing the smaller world rank is "A"
        and occupies slots [0, len(A)); returns (A, B, my slot)."""
        local, remote = self.group, self.remote_group
        a, b = (local, remote) if min(local) < min(remote) else (remote, local)
        _, world_rank = require_env()
        slot = (a.index(world_rank) if world_rank in a
                else len(a) + b.index(world_rank))
        return tuple(a), tuple(b), slot

    def two_group_channel(self):
        """The all-ranks-of-both-groups rendezvous used by intercomm
        collectives (MPI_ROOT semantics; /root/reference/src/comm.jl:135-162
        creates intercomms whose collectives libmpi honors). Returns
        (channel, my_slot, A, B)."""
        self._check()
        a, b, slot = self.two_group_slots()
        chan = self.ctx.channel(("inter", self.cid), len(a) + len(b),
                                group=a + b)
        return chan, slot, a, b

    def __repr__(self) -> str:
        return (f"<Intercomm {self.name} cid={self.cid} local={len(self.group)} "
                f"remote={len(self.remote_group)}>")


def spawn_argv() -> list:
    """The argv a spawned worker was launched with (empty outside a spawned
    rank). Spawned scripts read this instead of sys.argv — workers are threads
    of one process, so mutating the global sys.argv would race."""
    ctx, world_rank = require_env()
    return list(ctx.spawn_argv.get(world_rank, []))


def _worker_argv(command, argv) -> list:
    """The argv the spawned worker should see: the full list, minus the script
    entry when (and only when) the script was resolved *from* argv because
    ``command`` itself wasn't runnable (mirrors _run_spawned's resolution)."""
    argv = [str(a) for a in (argv or [])]
    if callable(command) or (isinstance(command, str) and command.endswith(".py")):
        return argv
    scripts = [a for a in argv if a.endswith(".py")]
    if scripts:
        argv = list(argv)
        argv.remove(scripts[0])
    return argv


def _run_spawned(command, argv):
    """Execute a spawned worker: a Python callable, or a .py script path
    (the analog of `mpiexec`-ing `julia spawned_worker.jl`,
    test/spawned_worker.jl:6-8). Script workers get their args via
    :func:`spawn_argv`, never via the (process-global) sys.argv."""
    if callable(command):
        command(*(argv or ()))
        return
    import runpy
    if isinstance(command, str) and command.endswith(".py"):
        script = command
    elif argv:
        scripts = [a for a in argv if str(a).endswith(".py")]
        if not scripts:
            raise MPIError(f"cannot spawn {command!r}: no python script in argv",
                           code=_ec.ERR_SPAWN)
        script = scripts[0]
    else:
        raise MPIError(f"cannot spawn {command!r}: pass a callable or a .py path",
                       code=_ec.ERR_SPAWN)
    runpy.run_path(script, run_name="__main__")


def Comm_spawn(command, argv=None, maxprocs: int = 1, comm: Comm = COMM_WORLD,
               errors=None, **info) -> Intercomm:
    """Collectively spawn ``maxprocs`` new ranks running ``command`` (a Python
    callable or script path), returning the parent side of an intercomm
    (src/comm.jl:135-147).

    OS-process spawn has no ICI analog (SURVEY.md §2.2): new ranks join the
    same controller process as fresh rank-threads with their own COMM_WORLD,
    the host-level emulation the survey prescribes."""
    _record_coll(comm, f"Comm_spawn@{comm.cid}")
    my_rank = comm.rank()
    parent_group = comm.group
    ctx = comm.ctx
    worker_argv = _worker_argv(command, argv)

    # A comparable identity for `command` (ADVICE r1): ranks disagreeing on
    # WHAT to spawn must be detected, not resolved by whichever rank's
    # closure runs the combine. Callables compare by qualified name + module.
    if callable(command):
        command_id = (getattr(command, "__module__", ""),
                      getattr(command, "__qualname__", repr(command)))
    else:
        command_id = str(command)
    contrib = (int(maxprocs), command_id, tuple(worker_argv))

    if hasattr(ctx, "spawn_processes"):
        # Multi-process tier: the star-root process launches real child OS
        # processes that join the transport mesh (the honest analog of
        # libmpi spawning via the process manager, src/comm.jl:135-147);
        # every parent then grows its world view.
        def combine_procs(cs):
            if any(c != cs[0] for c in cs[1:]):
                from .error import CollectiveMismatchError
                raise CollectiveMismatchError(
                    f"Comm_spawn arguments disagree across ranks: {cs!r}")
            return [ctx.spawn_processes(int(maxprocs), command, argv,
                                        parent_group)] * len(cs)

        child_group, inter_cid, _world_cid, world_addrs = comm.channel().run(
            my_rank, contrib, combine_procs, f"Comm_spawn@{comm.cid}")
        ctx.apply_growth(world_addrs)
        if errors is not None:
            errors[:] = [0] * int(maxprocs)
        return Intercomm(parent_group, tuple(child_group), inter_cid,
                         name="spawn_intercomm")

    def combine(cs):
        # Spawn is collective: every parent rank must agree on what to spawn
        # (libmpi validates root-side args; here all ranks contribute, so
        # disagreement must fail loudly, not be resolved by arrival order).
        if any(c != cs[0] for c in cs[1:]):
            from .error import CollectiveMismatchError
            # no sorted(): contribs may be heterogeneous (str vs tuple
            # command ids) and must still produce THIS error, not TypeError
            raise CollectiveMismatchError(
                f"Comm_spawn arguments disagree across ranks: {cs!r}")
        world_cid = ctx.alloc_cid()
        inter_cid = ctx.alloc_cid()
        child_group = ctx.add_ranks(int(maxprocs), world_cid)
        for r in child_group:
            # Each child gets its own handle: freeing one must not invalidate
            # a sibling's (MPI handles are per-process).
            ctx.parent_comm[r] = Intercomm(child_group, parent_group, inter_cid,
                                           name="parent_intercomm")
            ctx.spawn_argv[r] = list(worker_argv)
            ctx.start_rank_thread(r, lambda: _run_spawned(command, argv))
        return [(child_group, inter_cid)] * len(cs)

    child_group, inter_cid = comm.channel().run(
        my_rank, contrib, combine, f"Comm_spawn@{comm.cid}")
    if errors is not None:
        errors[:] = [0] * int(maxprocs)
    return Intercomm(parent_group, child_group, inter_cid, name="spawn_intercomm")


def Comm_get_parent() -> Comm:
    """The intercomm to the spawning job, or COMM_NULL (src/comm.jl:123-127)."""
    ctx, world_rank = require_env()
    return ctx.parent_comm.get(world_rank, COMM_NULL)


def _epoch_view(ctx, world_rank) -> dict:
    """This rank's agreement-epoch state, per communicator: the slice of
    ``ctx._agree_seq`` keyed by ``world_rank``. Contributed to
    Intercomm_merge so ranks joining an older (possibly shrunk) world can
    adopt its epoch space instead of silently diverging from it."""
    seq = getattr(ctx, "_agree_seq", None) or {}
    return {cid: e for (cid, r), e in seq.items() if r == world_rank}


def Intercomm_merge(intercomm: Intercomm, high: bool) -> Comm:
    """Collectively merge an intercomm's two groups into one intracomm
    (src/comm.jl:155-162). Groups whose members pass ``high=False`` are
    ordered first.

    Merging into a *shrunk* world is supported: every rank contributes its
    per-communicator agreement-epoch view, established members must agree
    on theirs (a divergence is a loud ``MPIError``, never a silently forked
    cid space), and joining ranks adopt the agreed epochs — so a later
    ``Comm_agree``/``Comm_shrink`` on a pre-existing communicator derives
    the same epoch (and thus the same shrink cid) on old and new ranks
    alike. The merged channel is registered eagerly so the new comm is
    usable while ``failed_ranks`` is non-empty (same contract as
    ``Comm_shrink``)."""
    if not isinstance(intercomm, Intercomm):
        raise MPIError("Intercomm_merge requires an intercommunicator",
                       code=_ec.ERR_COMM)
    _record_coll(intercomm, f"Intercomm_merge@{intercomm.cid}")
    ctx = intercomm.ctx
    a, b, slot = intercomm.two_group_slots()
    _, world_rank = require_env()
    total = len(a) + len(b)
    chan = ctx.channel(("merge", intercomm.cid), total, group=a + b)

    def combine(cs):
        cid = ctx.alloc_cid()
        lows = [(s, wr) for s, (wr, hi, _v) in enumerate(cs) if not hi]
        highs = [(s, wr) for s, (wr, hi, _v) in enumerate(cs) if hi]
        merged = tuple(wr for _, wr in lows) + tuple(wr for _, wr in highs)
        views: dict = {}
        for wr, _hi, view in cs:
            for vcid, e in view.items():
                views.setdefault(vcid, {})[wr] = e
        adopt = {}
        for vcid, per in views.items():
            if len(set(per.values())) > 1:
                raise MPIError(
                    f"Intercomm_merge: agreement-epoch mismatch on comm "
                    f"{vcid}: " + ", ".join(
                        f"world rank {r} at epoch {e}"
                        for r, e in sorted(per.items(), key=lambda kv:
                                           str(kv[0]))) +
                    " — the merging groups ran divergent agree/shrink "
                    "histories and would fork the shrink-cid space",
                    code=_ec.ERR_SPAWN)
            adopt[vcid] = next(iter(per.values()))
        return [(merged, cid, adopt)] * total

    merged, cid, adopt = chan.run(
        slot, (world_rank, bool(high), _epoch_view(ctx, world_rank)),
        combine, f"Intercomm_merge@{intercomm.cid}")
    seq = getattr(ctx, "_agree_seq", None)
    if seq is None:
        seq = ctx._agree_seq = {}
    for vcid, e in adopt.items():
        for wr in merged:
            seq.setdefault((vcid, wr), e)
    ctx.channel(cid, len(merged), tuple(merged))
    return Comm(merged, cid, name="merged")


def Comm_compare(comm1: Comm, comm2: Comm) -> Comparison:
    """Compare two communicators (src/comm.jl:197-218).

    IDENT: same context; CONGRUENT: same members, same order; SIMILAR: same
    members, different order; UNEQUAL otherwise.
    """
    if comm1 is comm2 or comm1.cid == comm2.cid:
        return Comparison.IDENT
    g1, g2 = comm1.group, comm2.group
    if g1 == g2:
        return Comparison.CONGRUENT
    if sorted(g1) == sorted(g2):
        return Comparison.SIMILAR
    return Comparison.UNEQUAL


def free(obj) -> None:
    """Release a communicator/window/datatype (src/handle.jl:50, src/comm.jl).

    No C resources back these objects; freeing marks them unusable (and a
    communicator's free() also reclaims its I-collective worker thread)."""
    if isinstance(obj, (_WorldComm, _SelfComm, _NullComm)):
        raise MPIError("cannot free a builtin communicator", code=_ec.ERR_COMM)
    if hasattr(obj, "free"):
        obj.free()
    elif hasattr(obj, "_freed"):
        obj._freed = True
