"""Ulysses-style sequence parallelism: all_to_all head↔sequence reshard.

Reference primitive: Alltoall! (SURVEY.md §2.5;
/root/reference/src/collective.jl:489-532). TPU realization: one
``lax.all_to_all`` flips which dimension is sharded — sequence-sharded
activations become head-sharded for exact local attention, then flip back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def seq_to_heads(x: jnp.ndarray, *, axis: str = "sp") -> jnp.ndarray:
    """(b, h, t/n, d) sequence-sharded → (b, h/n, t, d) head-sharded."""
    return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)


def heads_to_seq(x: jnp.ndarray, *, axis: str = "sp") -> jnp.ndarray:
    """(b, h/n, t, d) head-sharded → (b, h, t/n, d) sequence-sharded."""
    return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(q, k, v, *, axis: str = "sp", causal: bool = True):
    """Exact attention for sequence-sharded q/k/v via the head reshard."""
    qh = seq_to_heads(q, axis=axis)
    kh = seq_to_heads(k, axis=axis)
    vh = seq_to_heads(v, axis=axis)
    d = qh.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh * (d ** -0.5), kh)
    if causal:
        t = s.shape[-1]
        mask = jnp.tril(jnp.ones((t, t), dtype=bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return heads_to_seq(o, axis=axis)
