"""Ring attention: exact attention over sequence shards with a ppermute ring.

Reference primitives: the periodic Cart_shift + Sendrecv! ring machinery
(SURVEY.md §5 long-context; /root/reference/test/test_sendrecv.jl:100-115,
src/topology.jl:155-164). TPU realization: each rank holds a sequence block of
Q/K/V; K/V blocks rotate around the 'sp' mesh axis with ``lax.ppermute`` while
a flash-style online softmax accumulates — n_ring steps of compute overlapped
with neighbor DMA on the ICI ring, memory O(block²) instead of O(seq²).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jnp.ndarray:
    """Blockwise-exact attention over a sequence-sharded axis.

    q, k, v: (batch, heads, block_len, head_dim) — the local sequence block.
    Block b of the global sequence lives on rank b of ``axis``. Returns the
    local attention output block (same shape as q).
    """
    b, h, t, d = q.shape
    n = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)
    my = lax.axis_index(axis)
    scale = (d ** -0.5) if scale is None else scale
    q = q * scale

    if n == 1:
        # ring of one = plain local attention: skip the online-softmax
        # machinery so XLA fuses the whole block, and stay in the input
        # dtype (an f32 upcast here runs the attention matmuls on the slow
        # MXU path and cost 13% of a full bf16 train step, measured by
        # benchmarks/flagship_probe)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        if causal:
            qi = jnp.arange(t)[:, None]
            ki = jnp.arange(t)[None, :]
            s = jnp.where(qi >= ki, s, jnp.asarray(NEG_INF, s.dtype))
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v).astype(q.dtype)

    acc = jnp.zeros_like(q, dtype=jnp.float32)
    m = jnp.full((b, h, t, 1), NEG_INF, dtype=jnp.float32)   # running max
    l = jnp.zeros((b, h, t, 1), dtype=jnp.float32)           # running denom

    k_cur, v_cur = k, v
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src_block = (my - step) % n          # which global block k_cur holds
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur).astype(jnp.float32)
        if causal:
            # block-granular mask: future blocks fully masked, own block
            # triangular, past blocks unmasked.
            qi = jnp.arange(t)[:, None]
            ki = jnp.arange(t)[None, :]
            tri = jnp.where(qi >= ki, 0.0, NEG_INF)
            s = s + jnp.where(src_block == my, tri,
                              jnp.where(src_block > my, NEG_INF, 0.0))
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        # zero masked entries explicitly: when a whole row is masked both s
        # and m_new are NEG_INF and exp(s - m_new) would wrongly be 1.
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        correction = jnp.exp(jnp.maximum(m - m_new, NEG_INF))
        l = l * correction + p.sum(axis=-1, keepdims=True)
        acc = acc * correction + jnp.einsum("bhqk,bhkd->bhqd", p,
                                            v_cur.astype(jnp.float32))
        m = m_new
        if step != n - 1:
            k_cur = lax.ppermute(k_cur, axis, perm)
            v_cur = lax.ppermute(v_cur, axis, perm)

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)
