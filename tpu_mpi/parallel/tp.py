"""Tensor (model) parallelism: Megatron-style sharded matmuls.

Reference primitives: Allreduce!/Allgather!/Reduce_scatter over the model
axis (SURVEY.md §2.5; /root/reference/src/collective.jl:295-335,691-738).
TPU realization: column-parallel layers shard the output feature dim (no
communication), row-parallel layers shard the input feature dim and psum
partial products; the f/g identity-psum conjugate pair carries the right
gradients, and XLA schedules the psum on ICI overlapped with the matmul.
"""

from __future__ import annotations

from typing import Any, Optional

import jax


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ident(x, axis: str):
    return x


def _ident_fwd(x, axis):
    return x, None


def _ident_bwd(axis, _res, g):
    from jax import lax
    # f's input is replicated over `axis`, so the psum'd cotangent (invariant
    # over `axis`) already has the matching static type.
    return (lax.psum(g, axis),)


_ident.defvjp(_ident_fwd, _ident_bwd)


def tp_identity_fwd_psum_bwd(x: Any, axis: str = "tp"):
    """Megatron's ``f`` operator: identity forward, psum backward — placed
    where a replicated activation enters a column-parallel layer."""
    return _ident(x, axis)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _psum_op(x, axis: str):
    from jax import lax
    return lax.psum(x, axis)


def _psum_fwd(x, axis):
    from jax import lax
    return lax.psum(x, axis), None


def _psum_bwd(axis, _res, g):
    try:
        from jax._src.lax.parallel import pvary
    except ImportError:
        # pre-vma jax has no varying-axes type system; the identity
        # cotangent is already correct there.
        return (g,)
    # the cotangent flows back identically to every tp rank; mark it varying
    # to match the primal input's type.
    return (pvary(g, axis),)


_psum_op.defvjp(_psum_fwd, _psum_bwd)


def tp_psum_fwd_identity_bwd(x: Any, axis: str = "tp"):
    """Megatron's ``g`` operator: psum forward, identity backward — placed
    where row-parallel partial sums are combined."""
    return _psum_op(x, axis)


def column_parallel(x: Any, w_shard: Any, b_shard: Optional[Any] = None,
                    axis: str = "tp"):
    """y_shard = f(x) @ W_shard: output features sharded, no forward comm."""
    y = tp_identity_fwd_psum_bwd(x, axis) @ w_shard
    return y + b_shard if b_shard is not None else y


def row_parallel(x_shard: Any, w_shard: Any, b: Optional[Any] = None,
                 axis: str = "tp"):
    """y = g(x_shard @ W_shard): input features sharded, psum combines."""
    y = tp_psum_fwd_identity_bwd(x_shard @ w_shard, axis)
    return y + b if b is not None else y


def all_gather_output(y_shard: Any, axis: str = "tp", dim: int = -1):
    """Materialize a column-parallel output fully (e.g. for logits)."""
    from jax import lax
    if dim < 0:
        dim = y_shard.ndim + dim
    return lax.all_gather(y_shard, axis, axis=dim, tiled=True)
