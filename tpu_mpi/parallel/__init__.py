"""Parallelism strategies built on the primitive layer.

The reference provides the *primitives* these strategies are built from, not
the strategies themselves (SURVEY.md §2.5 maps each strategy to its
primitives). Here each is a first-class deliverable over ``tpu_mpi.xla``:

- data parallel (dp.py)      ← Allreduce of grads / Bcast of params
- tensor parallel (tp.py)    ← psum / all_gather / reduce_scatter
- sequence parallel (ring.py, ulysses.py) ← ppermute ring / all_to_all
- expert parallel (ep.py)    ← padded all_to_all with capacity masks
- pipeline parallel (pp.py)  ← ppermute microbatch rotation
- halo exchange (halo.py)    ← Cartesian ppermute of boundary slices
"""

from .dp import allreduce_grads, pmean_tree
from .tp import all_gather_output, column_parallel, row_parallel, tp_identity_fwd_psum_bwd, tp_psum_fwd_identity_bwd
from .ring import ring_attention
from .ulysses import heads_to_seq, seq_to_heads
from .ep import moe_dispatch_combine
from .pp import pipeline_forward
from .halo import halo_exchange
