"""Pipeline parallelism: microbatch rotation between stage neighbors.

Reference primitives: Send/Recv!/Isend/Irecv! between stage neighbors
(SURVEY.md §2.5; /root/reference/src/pointtopoint.jl:179-346). TPU
realization: stages live on ranks of a 'pp' mesh axis; activations advance
one stage per tick with ``lax.ppermute`` in a GPipe schedule — the
fill/steady/drain loop is a static unroll XLA pipelines on ICI, and the whole
thing is differentiable (grads ride the reverse permutation).
"""

from __future__ import annotations

from typing import Any, Callable

import jax.numpy as jnp
from jax import lax


def pipeline_forward(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                     params: Any, microbatches: jnp.ndarray, *,
                     axis: str = "pp") -> jnp.ndarray:
    """Run microbatches through a chain of stages.

    stage_fn(params, x): this rank's stage (params are the stage's own —
    already sharded over ``axis``). microbatches: (m, ...) — each rank feeds
    the same schedule; only rank 0's input matters, only the *last* stage's
    output is meaningful (others return zeros), mirroring how rooted MPI
    pipelines behave. Returns (m, ...) outputs on every rank (valid on the
    last stage).
    """
    n = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)
    my = lax.axis_index(axis)
    m = microbatches.shape[0]
    fwd = [(i, (i + 1) % n) for i in range(n)]

    carry = jnp.zeros_like(microbatches[0])
    outs = []
    ticks = m + n - 1
    for tick in range(ticks):
        # rank 0 injects a fresh microbatch while any remain
        inject = microbatches[min(tick, m - 1)]
        x = jnp.where(my == 0, jnp.where(tick < m, inject, jnp.zeros_like(inject)),
                      carry)
        y = stage_fn(params, x)
        # the last stage emits microbatch (tick - (n-1)) at this tick
        outs.append(y)
        carry = lax.ppermute(y, axis, fwd)
    # collect the last stage's emissions for ticks n-1 .. n-1+m-1
    result = jnp.stack(outs[n - 1:n - 1 + m])
    return result
