"""Halo exchange: boundary-slice trading on an N-d process grid.

Reference primitives: Cartesian comms + Sendrecv! with subarray datatypes
(SURVEY.md §2.5; /root/reference/test/test_sendrecv.jl:100-133,
src/datatypes.jl:171-190). TPU realization: two ``lax.ppermute`` calls per
grid dimension (one per direction) moving the boundary slices — the subarray
datatype becomes a plain lax.slice, and XLA overlaps the neighbor DMAs.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
from jax import lax


def halo_exchange(x: jnp.ndarray, *, axes: Sequence[str], halo: int = 1,
                  periodic: bool = True) -> jnp.ndarray:
    """Pad each spatial dim of the local block with neighbors' boundaries.

    x: local block, one array dim per mesh axis in ``axes`` (leading dims may
    be batch). Returns x padded by ``halo`` on both sides of each exchanged
    dim. Non-periodic edges receive zeros (the PROC_NULL analog —
    src/topology.jl:155-164)."""
    offset = x.ndim - len(axes)
    for d, axis in enumerate(axes):
        dim = offset + d
        n = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)
        fwd = [(i, (i + 1) % n) for i in range(n)] if periodic else \
            [(i, i + 1) for i in range(n - 1)]
        bwd = [(i, (i - 1) % n) for i in range(n)] if periodic else \
            [(i, i - 1) for i in range(1, n)]
        lo = lax.slice_in_dim(x, 0, halo, axis=dim)               # my low edge
        hi = lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim], axis=dim)
        from_prev = lax.ppermute(hi, axis, fwd)   # prev rank's high edge
        from_next = lax.ppermute(lo, axis, bwd)   # next rank's low edge
        x = jnp.concatenate([from_prev, x, from_next], axis=dim)
    return x
