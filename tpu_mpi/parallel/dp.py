"""Data parallelism: gradient synchronization over a 'data' mesh axis.

Reference primitives: Allreduce! of gradients + Bcast! of params
(SURVEY.md §2.5; /root/reference/src/collective.jl:691-738,29-42).
TPU realization: one ``lax.psum``/``pmean`` per gradient pytree inside the
compiled step — XLA overlaps the all-reduce with backward compute.
"""

from __future__ import annotations

from typing import Any


def allreduce_grads(grads: Any, axis: str = "dp", mean: bool = True) -> Any:
    """Sum (or average) a gradient pytree across the data axis."""
    import jax
    from jax import lax
    op = lax.pmean if mean else lax.psum
    return jax.tree_util.tree_map(lambda g: op(g, axis), grads)


def pmean_tree(tree: Any, axis: str = "dp") -> Any:
    """Average any pytree (metrics, losses) across the data axis."""
    import jax
    from jax import lax
    return jax.tree_util.tree_map(lambda x: lax.pmean(x, axis), tree)
