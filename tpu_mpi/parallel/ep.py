"""Expert parallelism: capacity-bounded token routing over an 'ep' axis.

Reference primitive: Alltoallv! — variable-size token routing (SURVEY.md §2.5;
/root/reference/src/collective.jl:545-578). TPU realization: XLA needs static
shapes, so variable counts become a fixed per-expert *capacity* with masking
(the padded-all_to_all strategy SURVEY.md §2.3 prescribes for `*v` ops);
one ``lax.all_to_all`` ships token buffers to their experts and one ships
results back.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def moe_dispatch_combine(tokens: jnp.ndarray, expert_idx: jnp.ndarray,
                         expert_fn: Callable[[jnp.ndarray], jnp.ndarray], *,
                         capacity: int, axis: str = "ep") -> jnp.ndarray:
    """Top-1 Mixture-of-Experts dispatch/combine.

    tokens: (t, d) local tokens; expert_idx: (t,) target expert (== rank on
    ``axis``) per token; expert_fn: the local expert applied to (n*capacity, d).
    Tokens over capacity are dropped (returned as zeros), the standard
    static-shape MoE contract. Returns (t, d).
    """
    t, d = tokens.shape
    n = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)

    # position of each token within its expert's capacity window
    onehot = jax.nn.one_hot(expert_idx, n, dtype=jnp.int32)       # (t, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot                     # 1-based
    slot = (pos.sum(axis=1) - 1).astype(jnp.int32)                # (t,)
    keep = slot < capacity

    # scatter local tokens into per-expert send buffers (n, capacity, d)
    send = jnp.zeros((n, capacity, d), tokens.dtype)
    send = send.at[expert_idx, jnp.clip(slot, 0, capacity - 1)].add(
        jnp.where(keep[:, None], tokens, 0.0))

    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=True)
    out = expert_fn(recv.reshape(n * capacity, d)).reshape(n, capacity, d)
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0, tiled=True)

    # gather results back to token order
    gathered = back[expert_idx, jnp.clip(slot, 0, capacity - 1)]
    return jnp.where(keep[:, None], gathered, 0.0)
