"""Expert parallelism: capacity-bounded token routing over an 'ep' axis.

Reference primitive: Alltoallv! — variable-size token routing (SURVEY.md §2.5;
/root/reference/src/collective.jl:545-578). TPU realization: XLA needs static
shapes, so variable counts become a fixed per-expert *capacity* with masking
(the padded-all_to_all strategy SURVEY.md §2.3 prescribes for `*v` ops);
one ``lax.all_to_all`` ships token buffers to their experts and one ships
results back.

Two realizations live here:

- :func:`moe_dispatch_combine` — the jit/shard_map path for training steps
  (static shapes, capacity masking, ``lax.all_to_all``);
- :func:`moe_host_dispatch_combine` — the host-path decode-step variant
  used by the inference engine (``tpu_mpi.infer``): true variable counts
  over :func:`tpu_mpi.Alltoallv` on an ``ep`` communicator, which routes
  every decode step through the algorithm-selection layer and the online
  bandit's decision point (``collective._maybe_explore``). Token routing
  is nonstationary traffic — exactly what the epsilon-greedy explorer was
  built for.
"""

from __future__ import annotations

import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Per-thread persistent count-exchange buffers, keyed by (cid, n). The
# count Alltoall has a FIXED signature (n int64 per rank, same comm)
# every decode step — reusing the same buffer objects is what lets the
# auto-arm signature table (PR 11 plan cache) promote it to an armed
# persistent collective instead of re-planning per step. Thread-local
# because in the thread tier every rank drives its own copy of this
# function concurrently over the shared comm object.
_count_bufs = threading.local()


def _count_exchange_bufs(cid: int, n: int):
    cache = getattr(_count_bufs, "m", None)
    if cache is None:
        cache = _count_bufs.m = {}
    key = (cid, n)
    if key not in cache:
        cache[key] = (np.zeros(n, np.int64), np.zeros(n, np.int64))
    return cache[key]


def moe_dispatch_combine(tokens: jnp.ndarray, expert_idx: jnp.ndarray,
                         expert_fn: Callable[[jnp.ndarray], jnp.ndarray], *,
                         capacity: int, axis: str = "ep") -> jnp.ndarray:
    """Top-1 Mixture-of-Experts dispatch/combine.

    tokens: (t, d) local tokens; expert_idx: (t,) target expert (== rank on
    ``axis``) per token; expert_fn: the local expert applied to (n*capacity, d).
    Tokens over capacity are dropped (returned as zeros), the standard
    static-shape MoE contract. Returns (t, d).
    """
    t, d = tokens.shape
    n = lax.axis_size(axis) if hasattr(lax, "axis_size") else lax.psum(1, axis)

    # position of each token within its expert's capacity window
    onehot = jax.nn.one_hot(expert_idx, n, dtype=jnp.int32)       # (t, n)
    pos = jnp.cumsum(onehot, axis=0) * onehot                     # 1-based
    slot = (pos.sum(axis=1) - 1).astype(jnp.int32)                # (t,)
    keep = slot < capacity

    # scatter local tokens into per-expert send buffers (n, capacity, d)
    send = jnp.zeros((n, capacity, d), tokens.dtype)
    send = send.at[expert_idx, jnp.clip(slot, 0, capacity - 1)].add(
        jnp.where(keep[:, None], tokens, 0.0))

    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0, tiled=True)
    out = expert_fn(recv.reshape(n * capacity, d)).reshape(n, capacity, d)
    back = lax.all_to_all(out, axis, split_axis=0, concat_axis=0, tiled=True)

    # gather results back to token order
    gathered = back[expert_idx, jnp.clip(slot, 0, capacity - 1)]
    return jnp.where(keep[:, None], gathered, 0.0)


def moe_host_dispatch_combine(tokens: np.ndarray, expert_idx: np.ndarray,
                              expert_fn: Callable[[np.ndarray], np.ndarray],
                              comm, *, capacity: int) -> np.ndarray:
    """Top-1 MoE dispatch/combine on the host path: rank == expert over an
    ``ep`` communicator, shipped with :func:`tpu_mpi.Alltoallv` (true
    variable counts — the padded-capacity trick is only an XLA constraint).

    tokens: (t, d) float32 local tokens (t may be 0); expert_idx: (t,)
    target rank per token; expert_fn: this rank's expert, applied row-wise
    to whatever tokens arrive. Tokens beyond ``capacity`` per destination
    are dropped and come back as exact zeros (same contract as the jit
    path). Returns (t, d), bitwise-deterministic for a fixed routing.

    Every call makes exactly two Alltoallv rendezvous (dispatch, combine)
    plus one int64 Alltoall for the return counts — three decision-point
    visits per layer round for the online autotuner. The engine's
    vectorized decode path concatenates ALL co-batched requests' rows
    into one call, so the per-peer counts come from the whole batch and
    the round count per step is independent of batch width; batching is
    pure data movement here (the expert below stays row-wise), which is
    why a batched round is bitwise identical to the same rows sent one
    request at a time. The count exchange reuses per-thread persistent
    buffers so its fixed signature repeats verbatim and can auto-arm.
    """
    from .. import collective as _c
    tokens = np.ascontiguousarray(tokens)
    if tokens.ndim != 2:
        tokens = tokens.reshape(-1, tokens.shape[-1] if tokens.size else 1)
    t, d = tokens.shape
    n = comm.size()
    idx = np.asarray(expert_idx, dtype=np.int64).reshape(-1)

    # sender-side capacity bound: the first `capacity` tokens per
    # destination in original token order (stable — routing determines the
    # drop set, not arrival jitter)
    picked = [np.flatnonzero(idx == e)[:capacity] for e in range(n)]
    scounts = [int(p.size) for p in picked]
    order = (np.concatenate(picked) if picked else
             np.zeros(0, np.int64)).astype(np.int64)
    send = tokens[order] if t else tokens.reshape(0, d)

    sbuf, rbuf = _count_exchange_bufs(comm.cid, n)
    sbuf[:] = scounts
    rbuf[:] = 0
    _c.Alltoall(sbuf, rbuf, 1, comm)
    rcounts = [int(c) for c in rbuf]
    sc_el = [c * d for c in scounts]
    rc_el = [c * d for c in rcounts]

    flat_in = np.zeros(sum(rc_el), tokens.dtype)
    _c.Alltoallv(np.ascontiguousarray(send.reshape(-1)), flat_in,
                 sc_el, rc_el, comm)
    arrived = flat_in.reshape(-1, d)

    # apply the expert one row at a time: a token's result can never
    # depend on how many neighbors happened to share its exchange (BLAS
    # picks shape-dependent summation orders for larger operands), which
    # is what makes greedy decode scheduler-order independent.
    out = np.empty_like(arrived)
    for i in range(arrived.shape[0]):
        out[i] = expert_fn(arrived[i:i + 1])[0]

    flat_back = np.zeros(sum(sc_el), tokens.dtype)
    _c.Alltoallv(np.ascontiguousarray(out.reshape(-1)), flat_back,
                 rc_el, sc_el, comm)
    combined = np.zeros((t, d), tokens.dtype)   # dropped rows: exact zeros
    if order.size:
        combined[order] = flat_back.reshape(-1, d)
    return combined
