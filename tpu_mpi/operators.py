"""Reduction operators.

Reference: /root/reference/src/operators.jl — Op handle (:20), predefined
BAND/BOR/BXOR/LAND/LOR/LXOR/MAX/MIN/PROD/SUM/REPLACE/NO_OP (:22-37), dispatch
mapping Julia functions to builtins (:39-45), custom OpWrapper via @cfunction +
MPI_Op_create (:56-88).

TPU mapping (SURVEY.md §2.2): ops are elementwise binary functions applied
array-at-a-time. Custom ops are *strictly easier* here — any jittable binary
function works both on the host path (applied to numpy/jax arrays directly)
and in-graph (compiled into the XLA collective); no function-pointer machinery.
"""

from __future__ import annotations

import operator as _pyop
from typing import Any, Callable, Optional


def _xp(a: Any):
    """numpy-or-jax.numpy for a value (host path works on both array types)."""
    mod = type(a).__module__
    if mod.startswith("jax") or "Array" in type(a).__name__ and "jax" in mod:
        import jax.numpy as jnp
        return jnp
    import numpy as np
    return np


def _is_jax(a: Any) -> bool:
    return type(a).__module__.startswith("jax")


class Op:
    """A reduction operator: an elementwise binary function.

    ``commutative`` gates re-associating algorithms (the multi-process ring
    allreduce); the in-process host path always reduces in rank order
    (deterministic, and what Scan/Exscan require). ``ufunc``, when set, is a
    numpy ufunc equivalent used for in-place reduction on hot paths.
    """

    def __init__(self, fn: Callable[[Any, Any], Any], commutative: bool = False,
                 name: Optional[str] = None, ufunc: Any = None):
        self.fn = fn
        self.commutative = commutative
        self.name = name or getattr(fn, "__name__", "custom")
        self.ufunc = ufunc

    def __call__(self, a: Any, b: Any) -> Any:
        try:
            return self.fn(a, b)
        except (TypeError, ValueError):
            # Scalar-only user function: apply elementwise (the analog of
            # OpWrapper's element loop, src/operators.jl:56-69).
            import numpy as np
            if _is_jax(a) or _is_jax(b):
                import jax.numpy as jnp
                a2, b2 = np.asarray(a), np.asarray(b)
                return jnp.asarray(np.frompyfunc(self.fn, 2, 1)(a2, b2).astype(a2.dtype))
            a2, b2 = np.asarray(a), np.asarray(b)
            out = np.frompyfunc(self.fn, 2, 1)(a2, b2)
            return out.astype(a2.dtype) if a2.dtype.kind != "O" else out

    def __repr__(self) -> str:
        return f"<Op {self.name}>"

    def __reduce__(self):
        # Predefined ops unpickle to their canonical singletons, so identity
        # checks (``op is REPLACE``) hold across process boundaries (the RMA
        # wire engine and cross-process collectives ship ops by pickle).
        if _PREDEFINED.get(self.name) is self:
            return (_predefined_op, (self.name,))
        return (Op, (self.fn, self.commutative, self.name, self.ufunc))


def _sum(a, b):
    return a + b


def _prod(a, b):
    return a * b


def _min(a, b):
    return _xp(a).minimum(a, b)


def _max(a, b):
    return _xp(a).maximum(a, b)


def _land(a, b):
    xp = _xp(a)
    out = xp.logical_and(a != 0, b != 0)
    return out.astype(getattr(a, "dtype", None)) if hasattr(a, "dtype") else type(a)(out)


def _lor(a, b):
    xp = _xp(a)
    out = xp.logical_or(a != 0, b != 0)
    return out.astype(getattr(a, "dtype", None)) if hasattr(a, "dtype") else type(a)(out)


def _lxor(a, b):
    xp = _xp(a)
    out = xp.logical_xor(a != 0, b != 0)
    return out.astype(getattr(a, "dtype", None)) if hasattr(a, "dtype") else type(a)(out)


def _band(a, b):
    return a & b


def _bor(a, b):
    return a | b


def _bxor(a, b):
    return a ^ b


def _replace(a, b):
    return b


def _no_op(a, b):
    return a


import numpy as _np

SUM = Op(_sum, commutative=True, name="SUM", ufunc=_np.add)
PROD = Op(_prod, commutative=True, name="PROD", ufunc=_np.multiply)
MIN = Op(_min, commutative=True, name="MIN", ufunc=_np.minimum)
MAX = Op(_max, commutative=True, name="MAX", ufunc=_np.maximum)
LAND = Op(_land, commutative=True, name="LAND")
LOR = Op(_lor, commutative=True, name="LOR")
LXOR = Op(_lxor, commutative=True, name="LXOR")
BAND = Op(_band, commutative=True, name="BAND", ufunc=_np.bitwise_and)
BOR = Op(_bor, commutative=True, name="BOR", ufunc=_np.bitwise_or)
BXOR = Op(_bxor, commutative=True, name="BXOR", ufunc=_np.bitwise_xor)
REPLACE = Op(_replace, commutative=False, name="REPLACE")
NO_OP = Op(_no_op, commutative=False, name="NO_OP")

_PREDEFINED = {op.name: op for op in (SUM, PROD, MIN, MAX, LAND, LOR, LXOR,
                                      BAND, BOR, BXOR, REPLACE, NO_OP)}


def _predefined_op(name: str) -> Op:
    return _PREDEFINED[name]


def is_elementwise(op: Op) -> bool:
    """True when ``op`` is KNOWN to act independently per element — every
    predefined op, plus anything carrying a numpy ufunc. Chunk-separable
    transforms (the overlap engine's pipelined folds) require this: an
    arbitrary user callable might couple elements across the array, so it
    stays on the monolithic fold."""
    return op.ufunc is not None or _PREDEFINED.get(op.name) is op


def acc_combine(old: Any, incoming: Any, op: Op):
    """MPI accumulate semantics for a target range: the new target values,
    or None to leave the target unchanged (NO_OP). The single owner of the
    REPLACE/NO_OP dispatch used by both the in-process path
    (onesided._apply_op) and the cross-process wire engine
    (_rma_wire.ProcWinState.apply_acc)."""
    if op is REPLACE:
        return _np.asarray(incoming, dtype=old.dtype)
    if op is NO_OP:
        return None
    return _np.asarray(op(old, _np.asarray(incoming, dtype=old.dtype)))

# Function → builtin Op dispatch (src/operators.jl:39-45 maps + * min max & | ⊻).
_BUILTIN_MAP: dict[Any, Op] = {
    _pyop.add: SUM,
    _pyop.mul: PROD,
    min: MIN,
    max: MAX,
    _pyop.and_: BAND,
    _pyop.or_: BOR,
    _pyop.xor: BXOR,
    sum: SUM,
}


def as_op(op: Any) -> Op:
    """Normalize a user-supplied operator: Op | known builtin fn | any callable."""
    if isinstance(op, Op):
        return op
    mapped = _BUILTIN_MAP.get(op)
    if mapped is not None:
        return mapped
    if callable(op):
        return Op(op)
    raise TypeError(f"not a reduction operator: {op!r}")
