"""Reduce: sum every rank's value at the root; custom ops are any
jittable/callable binary function.

Run: tpurun --sim 4 examples/03-reduce.py
(the tpu_mpi analog of the reference's docs/examples/03-reduce.jl)
"""

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
root = 0
r = MPI.Comm_rank(comm)

sr = MPI.Reduce(r, MPI.SUM, root, comm)

if r == root:
    print(f"sum of ranks = {sr}")

MPI.Finalize()
