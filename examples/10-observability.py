"""Observability: pvar counters, Pcontrol, and the merged Perfetto trace.

Run: tpurun --sim 4 examples/10-observability.py
With tracing:  TPU_MPI_TRACE=1 tpurun --sim 4 examples/10-observability.py
  (writes the merged trace to $TPU_MPI_EXAMPLE_TRACE or /tmp/tpu_mpi_trace.json
   — load it at ui.perfetto.dev or chrome://tracing)
With dumps:    TPU_MPI_PVARS_DUMP=/tmp/pv tpurun --sim 4 examples/10-observability.py
               tpurun --stats /tmp/pv
See docs/observability.md.
"""

import os

import numpy as np

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
rank = MPI.Comm_rank(comm)
size = MPI.Comm_size(comm)

# some traffic worth counting: a few Allreduces and a ring Sendrecv
x = np.arange(4096, dtype=np.float64) + rank
y = np.empty_like(x)
for _ in range(5):
    MPI.Allreduce(x, y, MPI.SUM, comm)

token = np.array([float(rank)])
out = np.empty_like(token)
MPI.Sendrecv(token, (rank + 1) % size, 17, out, (rank - 1) % size, 17, comm)

MPI.Barrier(comm)

# per-comm counters, MPI_T style (always on unless TPU_MPI_PVARS=0)
s = comm.get_pvars()
if rank == 0:
    print(f"ops: {s['ops']}")
    print(f"p2p: {s['sends']} sends / {s['bytes_sent']} B out, "
          f"{s['recvs']} recvs / {s['bytes_recv']} B in")
    print("phase_s:", {k: round(v, 6) for k, v in s["phase_s"].items()})

# with TPU_MPI_TRACE=1 every op above carries wall-clock spans — merge all
# ranks into one Chrome-trace JSON (rank 0 writes, others pass through)
if MPI.analyze.last_trace() is not None:
    path = os.environ.get("TPU_MPI_EXAMPLE_TRACE", "/tmp/tpu_mpi_trace.json")
    MPI.analyze.timeline.merge_trace(comm, path)
    if rank == 0:
        print(f"merged trace -> {path}")

MPI.Finalize()          # flushes pvars-rank<R>.json when TPU_MPI_PVARS_DUMP set
