"""Checkpoint/resume through the parallel File layer.

The reference ships no checkpoint subsystem — MPI.File collective I/O IS
the substrate applications build it from (SURVEY.md §5 "Checkpoint /
resume"). This example does exactly that for a sharded training state:
every rank owns a shard of the parameters, all ranks write their shards
into ONE checkpoint file at rank-computed offsets with a collective
`write_at_all`, the "job" restarts (state zeroed), and a collective
`read_at_all` restores every shard — then training-state equality is
asserted.

Run: tpurun --sim 4 examples/08-checkpoint.py
"""

import os
import tempfile

import numpy as np

import tpu_mpi as MPI

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)

SHARD = 1024            # float64 elements per rank
rng = np.random.default_rng(rank)
params = rng.standard_normal(SHARD)          # this rank's parameter shard
step = np.array([17.0 + rank])               # plus a tiny per-rank scalar

path = os.path.join(tempfile.gettempdir(), "tpu_mpi_ckpt_example.bin")
if rank == 0 and os.path.exists(path):
    os.remove(path)
MPI.Barrier(comm)

# --- save: one file, every rank writes its shard collectively --------------
fh = MPI.File.open(comm, path, write=True, create=True)
base = rank * (SHARD + 1) * 8                # bytes: shard + step scalar
MPI.File.write_at_all(fh, base, params)
MPI.File.write_at_all(fh, base + SHARD * 8, step)
MPI.File.sync(fh)
MPI.File.close(fh)

# --- "restart": lose the in-memory state -----------------------------------
restored = np.zeros(SHARD)
restored_step = np.zeros(1)

# --- resume: collective read of every shard --------------------------------
fh = MPI.File.open(comm, path, read=True)
MPI.File.read_at_all(fh, base, restored)
MPI.File.read_at_all(fh, base + SHARD * 8, restored_step)
MPI.File.close(fh)

assert np.array_equal(restored, params)
assert restored_step[0] == 17.0 + rank
# the checkpoint is one coherent file: rank 0 can read any shard
# (File.open is collective over its communicator — COMM_SELF for a solo read)
if rank == 0:
    fh = MPI.File.open(MPI.COMM_SELF, path, read=True)
    other = np.zeros(SHARD)
    MPI.File.read_at(fh, (size - 1) * (SHARD + 1) * 8, other)
    MPI.File.close(fh)
    expect = np.random.default_rng(size - 1).standard_normal(SHARD)
    assert np.array_equal(other, expect)
    os.remove(path)
    print(f"checkpointed + restored {size} shards of {SHARD} f64 each: ok")
MPI.Barrier(comm)

MPI.Finalize()
