"""Continuous-batching MoE inference: two tenants generate on one pool.

The serve broker can host an expert-parallel MoE transformer on its warm
world (`tpurun --serve --infer`, docs/serving.md "Inference engine"): the
pool's ranks split into two pipeline stages, each stage's ranks are the
experts of its layers, and every decode step routes tokens through the
capacity-bounded Alltoallv dispatch/combine from tpu_mpi.parallel.ep.
Prefill activations stream between the stages over partitioned
point-to-point (Psend/Precv), so stage 1 consumes partition k while
stage 0 computes k+1.

This example attaches two tenants that generate *concurrently* — the
engine batches their prefills and decodes into shared steps — and then
replays one prompt alone to show the core contract: greedy token
sequences are bitwise identical no matter what else shared the batch.

Run:
    python examples/13-moe-serve.py

In real deployments:
    TPU_MPI_SESSION_TOKEN=s3cret tpurun --serve --infer --nranks 4
and any tenant streams tokens with
``serve.attach(...).generate(prompt, max_new=32)``.
"""

import threading

from tpu_mpi import serve

NRANKS = 4
TOKEN = "example-token"
PROMPTS = {"alice": [1, 2, 3, 4, 5, 6, 7], "bob": list(range(40, 56))}
MAX_NEW = 12


def tenant(address: str, name: str, out: dict) -> None:
    s = serve.attach(address, token=TOKEN, tenant=name)
    try:
        streamed = []
        toks = s.generate(PROMPTS[name], max_new=MAX_NEW,
                          on_token=streamed.append)
        assert streamed == toks          # the stream IS the sequence
        out[name] = toks
    finally:
        s.detach()


def main() -> None:
    broker = serve.Broker(nranks=NRANKS, token=TOKEN, infer=True)
    broker.run_in_thread()
    eng = broker.infer_engine
    print(f"broker: warm MoE pool at {broker.address} — "
          f"2 stages x {eng.ep} experts, "
          f"d_model={eng.cfg.d_model}, vocab={eng.cfg.vocab}")

    # two tenants decode concurrently: their steps share the batch
    results: dict = {}
    threads = [threading.Thread(target=tenant,
                                args=(broker.address, name, results))
               for name in PROMPTS]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for name in PROMPTS:
        print(f"{name}: {PROMPTS[name][:4]}... -> {results[name]}")

    # determinism: alice's prompt replayed alone matches her batched run
    s = serve.attach(broker.address, token=TOKEN, tenant="replay")
    solo = s.generate(PROMPTS["alice"], max_new=MAX_NEW)
    s.detach()
    assert solo == results["alice"], (solo, results["alice"])

    inf = broker.stats()["infer"]
    print(f"engine: {inf['completed']} requests, {inf['tokens']} tokens in "
          f"{inf['steps']} steps, peak KV "
          f"{inf['kv']['peak_in_use_max']}/{inf['kv']['blocks_per_rank']} "
          f"blocks/rank")
    broker.close()
    print("done: batched and solo greedy decode agree bitwise")


if __name__ == "__main__":
    main()
