"""Partitioned communication: compute/transfer overlap, MPI-4 style.

A two-stage pipeline: the producer rank computes its output microbatch
slice by slice, marking each partition ready the moment it is valid —
the partition ships immediately, overlapping the remaining compute. The
consumer starts working on early partitions (Parrived) while later ones
are still in flight. This is the MPI-4 API shape of what a TPU pipeline
stage does with its microbatch activations (tpu_mpi.parallel.pp moves the
same data in-graph with ppermute; this is the host-tier analog).

Run: tpurun --sim 2 examples/09-partitioned.py
"""

import time

import numpy as np

import tpu_mpi as MPI

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)
assert size >= 2, "run with at least 2 ranks"

PARTS, PLEN = 8, 4096
consumer = size - 1

if rank == 0:
    out = np.zeros(PARTS * PLEN)
    sreq = MPI.Psend_init(out, PARTS, consumer, 42, comm)
    MPI.Start(sreq)
    for i in range(PARTS):
        # "compute" partition i, then hand it to the transport at once
        sl = slice(i * PLEN, (i + 1) * PLEN)
        out[sl] = np.sqrt(np.arange(i * PLEN, (i + 1) * PLEN, dtype=np.float64))
        MPI.Pready(sreq, i)
    MPI.Wait(sreq)
    print(f"producer: {PARTS} partitions of {PLEN} f64 shipped as computed")
elif rank == consumer:
    buf = np.zeros(PARTS * PLEN)
    rreq = MPI.Precv_init(buf, PARTS, 0, 42, comm)
    MPI.Start(rreq)
    # consume in order, starting as soon as each partition lands
    checksum = 0.0
    for i in range(PARTS):
        deadline = time.monotonic() + 60
        while not MPI.Parrived(rreq, i):
            assert time.monotonic() < deadline
            time.sleep(0.0005)
        sl = slice(i * PLEN, (i + 1) * PLEN)
        checksum += float(buf[sl].sum())          # consume early partition
    MPI.Wait(rreq)
    expect = float(np.sqrt(np.arange(PARTS * PLEN, dtype=np.float64)).sum())
    assert abs(checksum - expect) < 1e-6 * expect
    print(f"consumer: processed every partition on arrival, checksum ok")

MPI.Barrier(comm)
MPI.Finalize()
