"""The TPU-native face: the same collectives as compiled XLA ops inside
jit/shard_map over a device mesh — zero host round-trips, differentiable,
overlappable with compute. This is where the framework outgrows the
reference (whose collectives always cross the FFI boundary into libmpi).

Run: tpurun --sim 8 examples/05-ingraph.py   (single rank drives the mesh)
"""

import numpy as np

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
if MPI.Comm_rank(comm) == 0:
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_mpi import xla

    n = len(jax.devices())
    mesh = xla.world_mesh("x")

    @jax.jit
    def step(x):
        f = jax.shard_map(lambda v: xla.allreduce(v, MPI.SUM, axis="x"),
                          mesh=mesh, in_specs=P("x"), out_specs=P())
        return f(x)

    x = jnp.arange(float(n * 4))
    out = step(x)
    expect = np.asarray(x).reshape(n, 4).sum(axis=0)
    assert np.allclose(np.asarray(out), expect)
    print(f"in-graph psum over {n} devices: {np.asarray(out)}")

MPI.Barrier(comm)
MPI.Finalize()
