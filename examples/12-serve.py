"""The serve tier: one warm broker, two tenants, zero cold starts.

A `tpurun --serve` broker owns a warm Init'd world and leases slices of it
to client sessions (docs/serving.md). This example runs the whole cast in
one script so it needs no orchestration: the broker is started in-process
exactly as `tpurun --serve` would, then two tenant clients attach over
loopback TCP and run disjoint collectives concurrently — each on its own
cid namespace, each metered in the broker's per-tenant ledger.

Run:
    python examples/12-serve.py

In real deployments the broker is its own daemon:
    TPU_MPI_SESSION_TOKEN=s3cret tpurun --serve --nranks 4 \
        --socket 127.0.0.1:7900
and each tenant is any process that calls
``serve.attach("127.0.0.1:7900", token="s3cret")`` — or, dressed in the
standard lifecycle, ``MPI.Init(session="127.0.0.1:7900")`` followed by
``MPI.serve.current_session()``.
"""

import threading
import time

import numpy as np

from tpu_mpi import serve

NRANKS = 4
TOKEN = "example-token"


def tenant(address: str, name: str, scale: float, out: dict) -> None:
    """One tenant's whole life: attach (sub-ms), compute, detach."""
    t0 = time.perf_counter()
    s = serve.attach(address, token=TOKEN, tenant=name)
    attach_ms = (time.perf_counter() - t0) * 1e3
    try:
        # per-rank contributions: rank i brings scale * (i + 1) everywhere
        parts = [np.full(8, scale * (i + 1), np.float32)
                 for i in range(NRANKS)]
        total = s.allreduce(parts)                      # sum over ranks
        peak = s.allreduce(np.full(4, scale), op="max")

        sub = s.comm_dup()                              # stays in-namespace
        ones = s.allreduce(np.ones(4, np.int64), comm=sub)
        s.comm_free(sub)

        s.pcontrol(2)                                   # flush the ledger
        out[name] = {"attach_ms": attach_ms, "total": total,
                     "peak": peak, "ones": ones,
                     "cids": (s.cid_base, s.cid_limit)}
    finally:
        s.detach()


def main() -> None:
    broker = serve.Broker(nranks=NRANKS, token=TOKEN)
    broker.run_in_thread()
    print(f"broker: warm pool of {NRANKS} ranks at {broker.address}")

    results: dict = {}
    threads = [threading.Thread(target=tenant,
                                args=(broker.address, name, scale, results))
               for name, scale in (("alice", 1.0), ("bob", 100.0))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for name in ("alice", "bob"):
        r = results[name]
        lo, hi = r["cids"]
        print(f"{name}: attached in {r['attach_ms']:.2f} ms, "
              f"cids [{lo}, {hi}), "
              f"sum={r['total'][0]:.0f}, max={r['peak'][0]:.0f}, "
              f"ones={r['ones'][0]}")

    # the broker's view: per-tenant admitted/measured books
    report = broker.ledger.report()["tenants"]
    for name in ("alice", "bob"):
        e = report[name]
        print(f"ledger[{name}]: admitted {e['admitted_ops']} ops / "
              f"{e['admitted_bytes']} B, measured "
              f"{e['measured'].get('coll_ops', 0)} collective ops")

    assert results["alice"]["total"][0] == 10.0 * 1.0
    assert results["bob"]["total"][0] == 10.0 * 100.0
    a0, a1 = results["alice"]["cids"]
    b0, b1 = results["bob"]["cids"]
    assert a1 <= b0 or b1 <= a0
    broker.close()
    print("done: two tenants, one warm pool, disjoint namespaces")


if __name__ == "__main__":
    main()
