"""Communication/compute overlap with nonblocking and neighborhood
collectives (MPI-3 features beyond the reference v0.14.2).

The canonical data-parallel training-step shape: kick off the gradient
Allreduce nonblockingly, overlap local work (the next microbatch's
forward), then complete — plus a stencil halo via one
``Neighbor_allgather`` call instead of 2*ndims Sendrecvs.

Run: tpurun --sim 4 examples/07-overlap.py
"""

import numpy as np

import tpu_mpi as MPI

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)

# --- nonblocking allreduce overlapped with local compute -------------------
grads = np.full(1 << 14, float(rank + 1), np.float32)
summed = np.zeros_like(grads)
req = MPI.Iallreduce(grads, summed, MPI.SUM, comm)

# "forward pass" of the next microbatch while the reduction is in flight
local = np.tanh(np.arange(4096, dtype=np.float32) * 1e-3).sum()

MPI.Wait(req)
assert np.all(summed == sum(range(1, size + 1)))

# a blocking collective is safe even with nonblocking ones outstanding:
# initiation order is preserved through the per-comm worker
req2 = MPI.Ibarrier(comm)
step = MPI.bcast({"step": 7} if rank == 0 else None, 0, comm)
MPI.Wait(req2)
assert step["step"] == 7

# --- one-call halo exchange on a periodic ring -----------------------------
ring = MPI.Cart_create(comm, 1, [size], [True], False)
r = MPI.Comm_rank(ring)
halos = MPI.Neighbor_allgather(np.full(3, float(r), np.float32), ring)
halos = np.asarray(halos).reshape(2, 3)      # [-1 neighbor, +1 neighbor]
assert halos[0, 0] == (r - 1) % size
assert halos[1, 0] == (r + 1) % size
MPI.free(ring)

if rank == 0:
    print(f"overlap ok: {size} ranks, local={local:.3f}, "
          f"grad sum={summed[0]:.0f}")
MPI.Finalize()
