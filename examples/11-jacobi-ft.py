"""Fault-tolerant Jacobi: the ULFM shrink → restore → continue recipe.

The same Laplace solver as examples/06-jacobi.py, wrapped in the recovery
loop from docs/fault-tolerance.md: every rank checkpoints its slab every
CKPT_EVERY sweeps (checkpoint.save_sharded — atomic rename + CRCs, so a
crash mid-save can never publish a torn file). When a rank dies, the
failure detector turns the survivors' pending halo exchanges and
Allreduces into typed ProcFailedError/RevokedError instead of hangs; they
revoke the communicator, shrink to the survivor set, reassemble the global
grid from the last checkpoint (reading the dead rank's shard with
``load_sharded(..., shard=i)``), re-partition it over the smaller world and
keep sweeping to the SAME tolerance.

Run (no failure — behaves like 06):
    tpurun --sim 4 examples/11-jacobi-ft.py

Run with an injected failure (rank 1 SIGKILLs itself at sweep 30):
    TPU_MPI_HEARTBEAT_MS=100 TPU_MPI_FT_KILL_SWEEP=30 \
        tpurun -n 4 --procs --sim 1 examples/11-jacobi-ft.py
"""

import os
import signal

import numpy as np

import tpu_mpi as MPI
from tpu_mpi import checkpoint
from tpu_mpi.error import ProcFailedError, RevokedError

N = 64          # global grid is N x N
TOL = 1e-4
MAX_SWEEPS = 5000
CKPT_EVERY = 20

KILL_SWEEP = int(os.environ.get("TPU_MPI_FT_KILL_SWEEP", "-1"))
KILL_RANK = int(os.environ.get("TPU_MPI_FT_KILL_RANK", "1"))

MPI.Init()
world = MPI.COMM_WORLD
world_rank = world.rank()
# one path per job, identical on every rank (the launcher is the parent)
CKPT = os.environ.get("TPU_MPI_FT_CKPT",
                      f"/tmp/jacobi-ft-{os.getppid()}.ckpt")


def partition(size: int):
    counts = [N // size + (1 if i < N % size else 0) for i in range(size)]
    starts = [0]
    for c in counts:
        starts.append(starts[-1] + c)
    return counts, starts


def restore_global(comm):
    """Reassemble the full grid from the last checkpoint, whatever world
    size wrote it (each survivor reads every shard — N is small; a large
    solver would read only the shards its new slab overlaps)."""
    shards = checkpoint.shard_count(CKPT, comm)
    blocks, sweep = [], 0
    for s in range(shards):
        t = checkpoint.load_sharded(CKPT, comm, shard=s)
        blocks.append(np.asarray(t["rows"]))
        sweep = int(np.asarray(t["sweep"])[0])
    return np.vstack(blocks), sweep


grid = np.zeros((N, N))      # interior rows; the hot edge is a halo row
sweeps = 0
comm = world
while True:
    rank, size = comm.rank(), comm.size()
    up = rank - 1 if rank > 0 else MPI.PROC_NULL
    down = rank + 1 if rank < size - 1 else MPI.PROC_NULL
    counts, starts = partition(size)
    rows = counts[rank]
    u = np.zeros((rows + 2, N))
    u[1:rows + 1] = grid[starts[rank]:starts[rank] + rows]
    if rank == 0:
        u[0, :] = 1.0                       # fixed hot top edge
    try:
        while sweeps < MAX_SWEEPS:
            MPI.Sendrecv(u[1], up, 0, u[rows + 1], down, 0, comm)
            MPI.Sendrecv(u[rows], down, 1, u[0], up, 1, comm)

            new = u[1:rows + 1].copy()
            new[:, 1:-1] = 0.25 * (u[:rows, 1:-1] + u[2:, 1:-1]
                                   + u[1:rows + 1, :-2] + u[1:rows + 1, 2:])
            local_res = float(np.max(np.abs(new - u[1:rows + 1])))
            u[1:rows + 1] = new
            sweeps += 1

            res = MPI.Allreduce(local_res, MPI.MAX, comm)
            if res < TOL:
                break
            if sweeps % CKPT_EVERY == 0:
                checkpoint.save_sharded(
                    CKPT, {"rows": u[1:rows + 1].copy(),
                           "sweep": np.array([sweeps])}, comm)
            if sweeps == KILL_SWEEP and world_rank == KILL_RANK:
                os.kill(os.getpid(), signal.SIGKILL)
        break                               # converged (or gave up)
    except (ProcFailedError, RevokedError) as e:
        print(f"rank {world_rank}: {type(e).__name__} at sweep {sweeps} — "
              f"revoking, shrinking, restoring", flush=True)
        MPI.Comm_revoke(comm)
        comm = MPI.Comm_shrink(comm)
        if comm is MPI.COMM_NULL:           # not a survivor
            MPI.Finalize()
            raise SystemExit(0)
        if os.path.exists(CKPT):
            grid, sweeps = restore_global(comm)
        else:
            grid, sweeps = np.zeros((N, N)), 0   # fault before first save
        continue

rank = comm.rank()
total_heat = MPI.Reduce(float(u[1:rows + 1].sum()), MPI.SUM, 0, comm)
if rank == 0:
    print(f"converged after {sweeps} sweeps on {comm.size()} rank(s) "
          f"(residual < {TOL}); total heat = {total_heat:.3f}", flush=True)
    assert sweeps < MAX_SWEEPS, "did not converge"
    assert total_heat > 0
print(f"OK-{world_rank}", flush=True)
MPI.Finalize()
