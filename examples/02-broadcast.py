"""Broadcast: typed arrays with Bcast, arbitrary objects with bcast.

Run: tpurun --sim 4 examples/02-broadcast.py
(the tpu_mpi analog of the reference's docs/examples/02-broadcast.jl,
which broadcasts a ComplexF64 array and then a Dict)
"""

import numpy as np

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
rank = MPI.Comm_rank(comm)
root = 0
N = 5

if rank == root:
    print(f" Running on {MPI.Comm_size(comm)} ranks")
MPI.Barrier(comm)

# typed path: every rank passes a same-shaped buffer; root's data wins
if rank == root:
    A = np.array([i * (1.0 + 2.0j) for i in range(1, N + 1)])
else:
    A = np.empty(N, dtype=np.complex128)
MPI.Bcast(A, root, comm)
print(f"rank = {rank}, A = {A}")

# object path: anything picklable ships whole (two-phase length+payload)
B = {"foo": "bar"} if rank == root else None
B = MPI.bcast(B, root, comm)
print(f"rank = {rank}, B = {B}")

# functions too — even closures — exactly like the reference's Julia
# Serialization (test/test_bcast.jl:38-55): each rank gets its own copy,
# by value, on the thread tier AND across OS processes (tpurun --procs)
k = 10
f = (lambda x: x + k) if rank == root else None
f = MPI.bcast(f, root, comm)
print(f"rank = {rank}, f(5) = {f(5)}")

MPI.Finalize()
