"""Nonblocking ring exchange: every rank Isends to its right neighbor and
Irecvs from its left, then waits on both requests.

Run: tpurun --sim 4 examples/04-sendrecv.py
(the tpu_mpi analog of the reference's docs/examples/04-sendrecv.jl)
"""

import numpy as np

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
rank = MPI.Comm_rank(comm)
size = MPI.Comm_size(comm)

dst = (rank + 1) % size
src = (rank - 1) % size

N = 4
send_mesg = np.full(N, float(rank))
recv_mesg = np.zeros(N)

rreq = MPI.Irecv(recv_mesg, src, src + 32, comm)
print(f"{rank}: Sending   {rank} -> {dst} = {send_mesg}")
sreq = MPI.Isend(send_mesg, dst, rank + 32, comm)

MPI.Waitall([rreq, sreq])
print(f"{rank}: Received {src} -> {rank} = {recv_mesg}")
assert np.all(recv_mesg == src)

MPI.Finalize()
