"""Elastic DDP training: bucketed-overlap gradient Allreduce + grow-back.

Data-parallel training of the toy transformer (tpu_mpi/models) on the
host-path training tier (docs/training.md): JAX computes loss and
gradients on each rank's own batch; ``tpu_mpi.train.DDPTrainer`` streams
the gradients, in reverse-layer order, through size-bounded buckets
riding persistent Allreduce handles — each bucket Started the moment its
last gradient lands, Waited just-in-time at the optimizer fold.

On top of the perf story sits the elastic one: every step checkpoints the
packed optimizer state sharded 1/nranks (PR 8 CRC'd format).  When a rank
dies mid-step the survivors revoke, shrink, ``Comm_spawn`` a replacement,
``Intercomm_merge`` it back, and EVERY rank (old and new) reloads from the
checkpoint — resharding across the new world — and keeps training.  The
batch for (step, rank) is seeded by (step, rank), so the mean gradient is
a fixed SET of per-rank contributions regardless of which process landed
on which rank after the resize: the loss curve is **bitwise identical**
to an uninterrupted run (rank 0 prints each loss as a float64 hex).

Run (no failure, thread tier):
    tpurun --sim 4 examples/14-ddp-train.py

Run with an injected failure at step 3 (procs tier, real SIGKILL):
    TPU_MPI_HEARTBEAT_MS=100 TPU_MPI_TRAIN_KILL_STEP=3 \
        tpurun -n 4 --procs --sim 1 examples/14-ddp-train.py

On the thread tier ranks are threads of ONE process, so a real SIGKILL
would take down the whole job; there the same knob injects the
failure-detector verdict instead (``ctx.peer_failed`` — exactly what the
heartbeat timeout produces on the procs tier) and the victim thread steps
out through the same typed-error recovery path.
"""

import os
import signal

import numpy as np

import tpu_mpi as MPI
from tpu_mpi.error import ProcFailedError, RevokedError

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax                                              # noqa: E402
import jax.numpy as jnp                                 # noqa: E402

from tpu_mpi.models.transformer import (                # noqa: E402
    TransformerConfig, _xent, transformer_forward, transformer_init)
from tpu_mpi.train import DDPTrainer                    # noqa: E402

CFG = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_seq=32)
BATCH, SEQ = 4, 16
STEPS = int(os.environ.get("TPU_MPI_TRAIN_STEPS", "6"))
KILL_STEP = int(os.environ.get("TPU_MPI_TRAIN_KILL_STEP", "-1"))
KILL_RANK = int(os.environ.get("TPU_MPI_TRAIN_KILL_RANK", "1"))
LAYER_KEYS = ("ln1", "w_qkv", "w_proj", "ln2", "w_in", "w_out")


def flatten(tree):
    """transformer_init's nested params -> flat name->array dict in
    forward order (the trainer feeds grads in reversed(dict) order)."""
    flat = {"embed": tree["embed"]}
    for i, layer in enumerate(tree["layers"]):
        for k in LAYER_KEYS:
            flat[f"layers.{i}.{k}"] = layer[k]
    flat["ln_f"] = tree["ln_f"]
    return flat


def unflatten(flat):
    """Trainer's float64 masters -> the float32 pytree the forward takes."""
    as_f32 = lambda a: jnp.asarray(a, jnp.float32)          # noqa: E731
    return {"embed": as_f32(flat["embed"]),
            "ln_f": as_f32(flat["ln_f"]),
            "layers": [{k: as_f32(flat[f"layers.{i}.{k}"])
                        for k in LAYER_KEYS}
                       for i in range(CFG.n_layers)]}


@jax.jit
def loss_and_grads(params, tokens, labels):
    def loss_fn(p):
        return _xent(transformer_forward(CFG, p, tokens), labels)
    return jax.value_and_grad(loss_fn)(params)


def batch_for(step, rank):
    """The (step, rank) batch.  Seeded by the RANK SLOT, not the process:
    after a resize the slots are re-dealt, but the set of per-rank
    contributions — and so the rank-ordered Allreduce — is unchanged."""
    rng = np.random.default_rng(1_000_003 * step + rank)
    toks = rng.integers(0, CFG.vocab, size=(BATCH, SEQ + 1))
    return (np.asarray(toks[:, :-1], dtype=np.int32),
            np.asarray(toks[:, 1:], dtype=np.int32))


def build_trainer(comm):
    params = flatten(transformer_init(jax.random.PRNGKey(0), CFG))
    return DDPTrainer(params, comm, lr=0.5, momentum=0.9,
                      bucket_bytes=1 << 14)


def die(comm, world_rank):
    if os.environ.get("TPU_MPI_PROC_RANK") is not None:
        os.kill(os.getpid(), signal.SIGKILL)    # procs tier: the real thing
    # thread tier: deliver the detector verdict by hand and leave through
    # the same typed error the surviving ranks will see
    comm.ctx.peer_failed(world_rank)
    raise ProcFailedError("injected failure (thread-tier SIGKILL analog)",
                          ranks=(world_rank,))


def main():
    MPI.Init()
    parent = MPI.Comm_get_parent()
    replacement = parent is not MPI.COMM_NULL
    if replacement:
        comm = MPI.Intercomm_merge(parent, True)
        ckpt = MPI.bcast(None, 0, comm)          # survivors know the path
        world_rank = -1                          # never a kill victim
    else:
        comm = MPI.COMM_WORLD
        world_rank = comm.rank()
        ckpt = os.environ.get(
            "TPU_MPI_TRAIN_CKPT", f"/tmp/ddp-train-{os.getppid()}.ckpt")
    FULL = comm.size()

    trainer = build_trainer(comm)
    step = trainer.load(ckpt) if replacement else 0
    losses = []
    while step < STEPS:
        try:
            if step == KILL_STEP and world_rank == KILL_RANK:
                die(comm, world_rank)
            tokens, labels = batch_for(step, comm.rank())
            loss, grads = loss_and_grads(unflatten(trainer.params),
                                         tokens, labels)
            gflat = flatten(grads)
            trainer.step((name, np.asarray(gflat[name]))
                         for name in reversed(list(gflat)))
            lsum = MPI.Allreduce(np.array([float(loss)]), MPI.SUM, comm)
            mean = float(lsum[0]) / comm.size()
            losses.append(mean)
            if comm.rank() == 0:
                print(f"step {step} loss {mean:.4f} "
                      f"hex {np.float64(mean).hex()}", flush=True)
            trainer.save(ckpt)                   # sharded 1/nranks, CRC'd
            step += 1
        except (ProcFailedError, RevokedError) as e:
            print(f"rank {world_rank}: {type(e).__name__} at step {step} — "
                  f"revoke, shrink, grow back, reshard", flush=True)
            MPI.Comm_revoke(comm)
            comm = MPI.Comm_shrink(comm)
            if comm is MPI.COMM_NULL:            # not a survivor
                MPI.Finalize()
                return
            inter = MPI.Comm_spawn(__file__, None, FULL - comm.size(), comm)
            comm = MPI.Intercomm_merge(inter, False)
            MPI.bcast(ckpt, 0, comm)             # replacements need the path
            trainer = build_trainer(comm)        # fresh handles on the new comm
            step = trainer.load(ckpt)            # reshard: resume bitwise
            continue

    MPI.Barrier(comm)
    if comm.rank() == 0:
        print(f"trained {STEPS} steps on {comm.size()} rank(s); loss "
              f"{losses[0]:.4f} -> {losses[-1]:.4f}, overlap fraction "
              f"{trainer.overlap_fraction():.2f}", flush=True)
        assert losses[-1] < losses[0], "loss did not decrease"
    print(f"OK-{world_rank if not replacement else 'spawned'}", flush=True)
    MPI.Finalize()


main()
