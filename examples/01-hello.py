"""Hello world: init, identify your rank, finalize.

Run: tpurun --sim 4 examples/01-hello.py
(the tpu_mpi analog of the reference's docs/examples/01-hello.jl)
"""

import tpu_mpi as MPI

MPI.Init()

comm = MPI.COMM_WORLD
print(f"Hello world, I am rank {MPI.Comm_rank(comm)} of {MPI.Comm_size(comm)}")
MPI.Barrier(comm)

MPI.Finalize()
