"""Distributed Jacobi iteration — the classic MPI application shape.

A 2-d Laplace solver on a 1-d process grid: each rank owns a slab of
rows, exchanges one-row halos with its Cartesian neighbors every sweep
(Sendrecv over Cart_shift, the reference's test_sendrecv.jl:100-133
pattern), and agrees on convergence with an Allreduce of the local
residuals. Fixed boundary: top edge held at 1, other edges at 0.

Run: tpurun --sim 4 examples/06-jacobi.py
"""

import numpy as np

import tpu_mpi as MPI

N = 64          # global grid is N x N
TOL = 1e-4
MAX_SWEEPS = 2000

MPI.Init()
comm = MPI.COMM_WORLD
rank, size = MPI.Comm_rank(comm), MPI.Comm_size(comm)

cart = MPI.Cart_create(comm, 1, [size], [False], False)
up, down = MPI.Cart_shift(cart, 0, 1)      # non-periodic: edges get PROC_NULL

rows = N // size + (1 if rank < N % size else 0)
# local slab with one halo row above and below
u = np.zeros((rows + 2, N))
if rank == 0:
    u[0, :] = 1.0                           # fixed hot top edge

sweeps = 0
while sweeps < MAX_SWEEPS:
    # halo exchange: my first real row goes up, my last real row goes down
    # a PROC_NULL partner skips that direction entirely (buffer untouched),
    # so rank 0's fixed top edge survives the exchange as-is
    MPI.Sendrecv(u[1], up, 0, u[rows + 1], down, 0, cart)
    MPI.Sendrecv(u[rows], down, 1, u[0], up, 1, cart)

    new = u[1:rows + 1].copy()
    new[:, 1:-1] = 0.25 * (u[:rows, 1:-1] + u[2:, 1:-1]
                           + u[1:rows + 1, :-2] + u[1:rows + 1, 2:])
    local_res = float(np.max(np.abs(new - u[1:rows + 1])))
    u[1:rows + 1] = new
    sweeps += 1

    res = MPI.Allreduce(local_res, MPI.MAX, comm)
    if res < TOL:
        break

total_heat = MPI.Reduce(float(u[1:rows + 1].sum()), MPI.SUM, 0, comm)
if rank == 0:
    print(f"converged after {sweeps} sweeps (residual < {TOL}); "
          f"total heat = {total_heat:.3f}")
    assert sweeps < MAX_SWEEPS, "did not converge"
    assert total_heat > 0

MPI.Finalize()
